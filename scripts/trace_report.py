#!/usr/bin/env python
"""Round-trace flight-recorder report: per-round critical path from JSONL.

Reads one or more run logs (the mlops sink's ``run_<id>.jsonl`` files —
pass every process's file for a multi-process session; spans carry
trace/span IDs, so the trees reassemble regardless of which file a span
landed in), rebuilds the trace trees, and prints where each round's wall
time went: straggler wait vs compute vs wire vs host.

    python scripts/trace_report.py ~/.cache/fedml_tpu/logs/run_0.jsonl
    python scripts/trace_report.py server.jsonl silo1.jsonl silo2.jsonl
    python scripts/trace_report.py run.jsonl --trace 4f2a...   # one tree

For every ROOT span (``round`` / ``pour`` / ``block``, the engine's
post-block per-round ``eval`` / ``checkpoint`` roots, plus orphans whose
parent lives in a file you didn't pass) the report shows the duration,
the per-category time (union of descendant span intervals clipped to the
root window, so overlapping spans never double-count), the attributed
fraction (the ≥95% acceptance bar: unattributed time is wall time no
span explains), the slowest descendants, and — for pours — the linked
contributing uploads with their per-link staleness.

Span-name → category map (keep in sync with the instrumentation):
  compute: train, dispatch, aggregate, eval
  wire:    comm.send, broadcast, upload, async.sync
  wait:    wait.uploads, wait.arrivals
  host:    host.input, host.close, checkpoint
Container spans (round, pour, block, silo.round) attribute through their
children, not themselves.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

CATEGORY = {
    "train": "compute", "dispatch": "compute", "aggregate": "compute",
    "eval": "compute",
    "comm.send": "wire", "broadcast": "wire", "upload": "wire",
    "async.sync": "wire",
    "wait.uploads": "wait", "wait.arrivals": "wait",
    "host.input": "host", "host.close": "host", "checkpoint": "host",
}
CONTAINERS = {"round", "pour", "block", "silo.round"}
# eval/checkpoint are the engine's post-block per-round roots (the fused
# block span is closed by the time they run, so they cannot be children)
ROOT_NAMES = ("round", "pour", "block", "eval", "checkpoint")


def load_spans(paths: List[str]) -> List[Dict[str, Any]]:
    spans = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("kind") == "span":
                    spans.append(rec)
    return spans


def union_len(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of [start, end] intervals."""
    total = 0.0
    end = -float("inf")
    for s, e in sorted(intervals):
        if e <= end:
            continue
        total += e - max(s, end)
        end = e
    return total


class Tree:
    def __init__(self, spans: List[Dict[str, Any]]):
        self.by_id = {s["span_id"]: s for s in spans}
        self.children = defaultdict(list)
        for s in spans:
            self.children[s.get("parent_id")].append(s)
        # a root is parentless OR references a parent we never saw (its
        # file was not passed) — report it anyway rather than dropping
        # the whole subtree silently
        self.roots = [s for s in spans
                      if s.get("parent_id") is None
                      or s["parent_id"] not in self.by_id]

    def descendants(self, span: Dict[str, Any]) -> List[Dict[str, Any]]:
        out, stack = [], [span["span_id"]]
        while stack:
            for c in self.children.get(stack.pop(), []):
                out.append(c)
                stack.append(c["span_id"])
        return out


def clip(span: Dict[str, Any], lo: float,
         hi: float) -> Optional[Tuple[float, float]]:
    s = max(float(span["start_ts"]), lo)
    e = min(float(span["end_ts"]), hi)
    return (s, e) if e > s else None


def analyze_root(tree: Tree, root: Dict[str, Any]) -> Dict[str, Any]:
    lo, hi = float(root["start_ts"]), float(root["end_ts"])
    dur = max(hi - lo, 1e-12)
    per_cat: Dict[str, List[Tuple[float, float]]] = defaultdict(list)
    covered: List[Tuple[float, float]] = []
    leaves: List[Dict[str, Any]] = []
    if root["name"] not in CONTAINERS:
        # a leaf root (engine eval/checkpoint, an orphaned worker span)
        # IS its own attribution — containers attribute through children
        covered.append((lo, hi))
        per_cat[CATEGORY.get(root["name"]) or "other"].append((lo, hi))
    for d in tree.descendants(root):
        iv = clip(d, lo, hi)
        if iv is None:
            continue
        cat = CATEGORY.get(d["name"])
        if d["name"] in CONTAINERS:
            # containers attribute through their children — but still
            # count toward coverage, so a remote silo.round whose inner
            # spans landed in an unpassed file is not "unattributed"
            covered.append(iv)
            continue
        covered.append(iv)
        per_cat[cat or "other"].append(iv)
        leaves.append(d)
    cats = {c: union_len(v) for c, v in per_cat.items()}
    leaves.sort(key=lambda s: s["end_ts"] - s["start_ts"], reverse=True)
    return {
        "root": root,
        "duration_s": dur,
        "categories": cats,
        "attributed_s": union_len(covered),
        "attributed_frac": min(union_len(covered) / dur, 1.0),
        "top": leaves[:3],
        "links": root.get("links", []),
        "events": root.get("events", []),
    }


def _label(span: Dict[str, Any]) -> str:
    attrs = span.get("attrs", {}) or {}
    for key in ("round_idx", "version", "start_round"):
        if key in attrs:
            return f"{span['name']}[{key}={attrs[key]}]"
    return span["name"]


def print_report(spans: List[Dict[str, Any]], only_trace: Optional[str],
                 min_attr: float, out=sys.stdout) -> int:
    by_trace: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    for s in spans:
        if only_trace is None or s["trace_id"].startswith(only_trace):
            by_trace[s["trace_id"]].append(s)
    if not by_trace:
        print("no span records found", file=out)
        return 1
    rows = []
    for trace_id in sorted(by_trace,
                           key=lambda t: min(s["start_ts"]
                                             for s in by_trace[t])):
        tree = Tree(by_trace[trace_id])
        for root in sorted(tree.roots, key=lambda s: s["start_ts"]):
            # genuinely-parentless non-round spans (a stray comm.send
            # outside any session span) stay out of the report, but an
            # ORPHAN — a subtree whose parent lives in a file that was
            # not passed (e.g. a silo log without the server's) — is
            # reported as its own root rather than dropped silently
            orphan = root.get("parent_id") is not None
            if (root["name"] not in ROOT_NAMES and not orphan
                    and only_trace is None):
                continue
            rows.append((trace_id, analyze_root(tree, root)))
    if not rows:
        print("no round/pour/block root spans found", file=out)
        return 1
    hdr = (f"{'root':<26} {'wall_s':>9} {'compute':>9} {'wire':>8} "
           f"{'wait':>8} {'host':>8} {'attr%':>6}  trace")
    print(hdr, file=out)
    print("-" * len(hdr), file=out)
    worst = 1.0
    for trace_id, a in rows:
        c = a["categories"]
        worst = min(worst, a["attributed_frac"])
        print(f"{_label(a['root']):<26} {a['duration_s']:>9.4f} "
              f"{c.get('compute', 0.0):>9.4f} {c.get('wire', 0.0):>8.4f} "
              f"{c.get('wait', 0.0):>8.4f} {c.get('host', 0.0):>8.4f} "
              f"{100.0 * a['attributed_frac']:>5.1f}%  {trace_id[:12]}",
              file=out)
        for t in a["top"]:
            print(f"    └ {_label(t):<24} {t['end_ts'] - t['start_ts']:.4f}s",
                  file=out)
        links = a["links"]
        if links:
            parts = []
            for ln in links:
                at = ln.get("attrs", {}) or {}
                parts.append(f"c{at.get('client', '?')}"
                             f"@s{at.get('staleness', '?')}")
            print(f"    ↳ links ({len(links)} uploads): "
                  + " ".join(parts), file=out)
        for ev in a["events"]:
            if ev["name"].startswith("chaos"):
                print(f"    ⚡ {ev['name']} {ev.get('attrs', {})}", file=out)
    n = len(rows)
    mean_attr = sum(a["attributed_frac"] for _, a in rows) / n
    print(f"\n{n} roots; attribution mean {100 * mean_attr:.1f}%, "
          f"min {100 * worst:.1f}%", file=out)
    if min_attr > 0 and worst < min_attr:
        print(f"FAIL: minimum attribution {100 * worst:.1f}% < "
              f"{100 * min_attr:.0f}% — wall time no span explains",
              file=out)
        return 2
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("logs", nargs="+",
                    help="run JSONL file(s) — pass every process's log")
    ap.add_argument("--trace", default=None,
                    help="only this trace id (prefix match)")
    ap.add_argument("--min-attr", type=float, default=0.0,
                    help="exit 2 if any root's attributed fraction is "
                         "below this (e.g. 0.95)")
    args = ap.parse_args(argv)
    spans = load_spans(args.logs)
    return print_report(spans, args.trace, args.min_attr)


if __name__ == "__main__":
    sys.exit(main())
