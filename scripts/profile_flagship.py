"""Per-stage profile of the flagship FedAvg ResNet-56/CIFAR round.

VERDICT r3 item 1: name where every microsecond of the ~2.7 s round goes.
Strategy: stage ablation on the REAL chip (the tunneled profiler UI is not
available) — time progressively simpler programs that share the flagship's
hot loop, so each delta isolates one stage:

  A. dispatch          — empty jitted fn + scalar readback (tunnel constant)
  B. sgd_stream bs=32  — shared-weight SGD scan, same total step count:
                         the per-step floor with ZERO federated machinery
  C. sgd_stream bs=256 — same at the roofline's perfect-batching size
                         (names the fixed per-op overhead amortization)
  D. local_loop        — scan over clients of run_local_sgd (dynamic-trip
                         while_loop + per-step batch gather + shuffle),
                         no schedule/accumulate/aggregate
  E. full_round        — the bench round (engine.run_round)

  B-A        = conv compute at the workload's real batch size
  D-B        = while_loop + gather + shuffle bookkeeping
  E-D        = schedule + update-accumulate + psum + server transform
               + per-round host work

Prints one JSON line per stage plus a summary split of the full round.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def _force(x):
    return float(jax.tree_util.tree_leaves(x)[0].sum())


def _time(fn, iters=3, warmup=1):
    for _ in range(warmup):
        _force(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        _force(fn())
    return (time.perf_counter() - t0) / iters


def main():
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.local_training import run_local_sgd
    from fedml_tpu.core.algframe.types import ClientData, TrainHyper
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    n_clients = 64
    args = Arguments(
        dataset="cifar10", model="resnet56", precision="bfloat16",
        client_num_in_total=n_clients, client_num_per_round=n_clients,
        comm_round=1, epochs=1, batch_size=32, learning_rate=0.1,
        frequency_of_the_test=10_000, random_seed=0,
        allow_synthetic=True, synthetic_size=50_000)
    fed, output_dim = load(args)
    bundle = create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate), epochs=1)

    mask = np.asarray(fed.train.mask)
    real_b = np.sum(np.any(mask.reshape(mask.shape[0], mask.shape[1], -1) > 0,
                           axis=-1), axis=-1)
    mean_real = float(real_b.mean())
    total_steps = int(round(n_clients * mean_real))
    print(json.dumps({"stage": "workload", "clients": n_clients,
                      "mean_real_batches": mean_real,
                      "total_steps": total_steps}), flush=True)

    rng = jax.random.PRNGKey(0)
    params = jax.device_put(bundle.init(rng, fed.train.x[0, 0]))
    tx = optax.sgd(0.1)

    # A. dispatch constant
    empty = jax.jit(lambda x: x + 1.0)
    t_disp = _time(lambda: empty(jnp.float32(0)), iters=5)
    print(json.dumps({"stage": "A_dispatch", "s": round(t_disp, 4)}),
          flush=True)

    # B/C. shared-weight SGD stream at bs 32 and 256
    def stream(bs, steps):
        x = jnp.zeros((bs, 32, 32, 3), jnp.float32)
        y = jnp.zeros((bs,), jnp.int32)
        m = jnp.ones((bs,), jnp.float32)
        batch = {"x": x, "y": y, "mask": m}

        def many(params, rng):
            opt_state = tx.init(params)

            def one(carry, i):
                p, s = carry
                (_, aux), g = jax.value_and_grad(spec.loss, has_aux=True)(
                    p, batch, jax.random.fold_in(rng, i))
                u, s = tx.update(g, s, p)
                return (optax.apply_updates(p, u), s), None

            (p, _), _ = jax.lax.scan(one, (params, opt_state),
                                     jnp.arange(steps))
            return p

        jf = jax.jit(many)
        return _time(lambda: jf(params, rng), iters=2)

    t_b32 = stream(32, total_steps)
    print(json.dumps({"stage": "B_sgd_stream_bs32", "s": round(t_b32, 4),
                      "per_step_ms": round(1e3 * (t_b32 - t_disp)
                                           / total_steps, 4)}), flush=True)
    steps256 = max(total_steps // 8, 1)
    t_b256 = stream(256, steps256)
    print(json.dumps({"stage": "C_sgd_stream_bs256", "s": round(t_b256, 4),
                      "per_step_ms_bs32equiv": round(
                          1e3 * (t_b256 - t_disp) / (steps256 * 8), 4)}),
          flush=True)

    # D. local loop over clients (while_loop + gather + shuffle), no engine.
    # Data is device_put OUTSIDE the timed region (a closure constant would
    # re-upload ~600 MB through the tunnel at compile time).
    dx = jax.device_put(fed.train.x)
    dy = jax.device_put(fed.train.y)
    dm = jax.device_put(fed.train.mask)

    def local_all(params, rng, dx, dy, dm):
        def per_client(carry, c):
            p0 = carry
            cdata = ClientData(x=dx[c], y=dy[c], mask=dm[c],
                               num_samples=jnp.float32(1.0))
            newp, _, mets = run_local_sgd(
                spec, tx, p0, cdata, jax.random.fold_in(rng, c), hyper)
            # FedAvg accumulate, same math as the engine
            return p0, jax.tree_util.tree_map(lambda a, b: b - a, p0, newp)

        _, deltas = jax.lax.scan(per_client, params,
                                 jnp.arange(n_clients))
        return jax.tree_util.tree_map(lambda d: d.mean(0), deltas)

    jl = jax.jit(local_all)
    t_local = _time(lambda: jl(params, rng, dx, dy, dm), iters=2)
    print(json.dumps({"stage": "D_local_loop", "s": round(t_local, 4),
                      "per_step_ms": round(1e3 * (t_local - t_disp)
                                           / total_steps, 4)}), flush=True)

    # E. full engine round
    opt = create_optimizer(args, spec)
    sim = TPUSimulator(args, fed, bundle, opt, spec)
    r = [0]

    def round_once():
        sim.run_round(r[0], hyper)
        r[0] += 1
        return sim.params

    t_round = _time(round_once, iters=3)
    print(json.dumps({"stage": "E_full_round", "s": round(t_round, 4),
                      "per_step_ms": round(1e3 * (t_round - t_disp)
                                           / total_steps, 4)}), flush=True)

    print(json.dumps({
        "stage": "SPLIT",
        "dispatch_s": round(t_disp, 4),
        "conv_compute_s(B-A)": round(t_b32 - t_disp, 4),
        "loop_bookkeeping_s(D-B)": round(t_local - t_b32, 4),
        "engine_overhead_s(E-D)": round(t_round - t_local, 4),
        "bs256_amortization_x(B/Cequiv)": round(
            (t_b32 - t_disp) / max(t_b256 - t_disp, 1e-9) / 8 * 8
            / (total_steps / (steps256 * 8)), 3),
    }), flush=True)


if __name__ == "__main__":
    main()
