"""The vmap-vs-scan experiment that killed `client_parallelism: vmap`
(VERDICT r3 item 1: the mode must win somewhere, or be deleted).

ResNet-18's channel widths (64..512) fill the MXU's 128-lane tiles — the
most favorable shipped config for client-lockstep batched convs. Measured
on the real chip (r4, 16 clients, bs 32, bf16):

    scan           0.419 s/round
    vmap chunk 4   0.613 s/round  (0.68x)
    vmap chunk 8   0.598 s/round  (0.70x)

vmap LOST by ~30% even here (XLA executes per-client-weight batched convs
per-group with a fixed ~10-25 us/group overhead), on top of losing on the
16..64-channel flagship in r3 — so the engine is scan-only and this
script documents the evidence. Re-running it now times scan twice (the
`client_parallelism` knob is gone); it is kept as the measurement record.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp


def _force(x):
    return float(jax.tree_util.tree_leaves(x)[0].sum())


def time_mode(mode: str, model: str, chunk: int = 8) -> float:
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    n_clients = 16
    args = Arguments(
        dataset="cifar10", model=model, precision="bfloat16",
        client_num_in_total=n_clients, client_num_per_round=n_clients,
        comm_round=1, epochs=1, batch_size=32, learning_rate=0.1,
        frequency_of_the_test=10_000, random_seed=0,
        allow_synthetic=True, synthetic_size=8_192,
        client_parallelism=mode, client_vmap_chunk=chunk)
    fed, output_dim = load(args)
    bundle = create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    opt = create_optimizer(args, spec)
    sim = TPUSimulator(args, fed, bundle, opt, spec)
    hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                       epochs=1)
    r = [0]

    def once():
        sim.run_round(r[0], hyper)
        r[0] += 1

    once()
    _force(sim.params)
    t0 = time.perf_counter()
    iters = 3
    for _ in range(iters):
        once()
        _force(sim.params)
    return (time.perf_counter() - t0) / iters


def main():
    model = sys.argv[1] if len(sys.argv) > 1 else "resnet18"
    t_scan = time_mode("scan", model)
    print(json.dumps({"model": model, "mode": "scan",
                      "round_s": round(t_scan, 4)}), flush=True)
    for chunk in (4, 8):
        t_vmap = time_mode("vmap", model, chunk)
        print(json.dumps({"model": model, "mode": f"vmap{chunk}",
                          "round_s": round(t_vmap, 4),
                          "speedup_vs_scan": round(t_scan / t_vmap, 3)}),
              flush=True)


if __name__ == "__main__":
    main()
