#!/usr/bin/env python
"""Compute-plane roofline report: per-op attribution from JSONL.

Reads run logs (the mlops sink's ``run_<id>.jsonl``) and renders the
``kind: roofline`` records the compute plane captured (one per compiled
program under ``obs_roofline: true``) plus any ``kind: recompile``
forensics records:

    python scripts/roofline_report.py run_0.jsonl
    python scripts/roofline_report.py run_0.jsonl --top 12 --program round
    python scripts/roofline_report.py old.jsonl --compare new.jsonl
    python scripts/roofline_report.py run_0.jsonl --min-attr 0.9

Per program: machine balance header (STATIC-ONLY flagged loudly on a CPU
mesh — shapes/FLOPs/bytes are exact there, the time/MFU columns are a
model), top-N ops by predicted time, a per-operand-shape aggregation
(the conv stream grouped by shape — the view the MFU-gap item needs),
the compute- vs memory-bound time split, and the collective-traffic
table (per-device wire bytes per execution, by collective kind and
replica-group size — the weak-scaling accounting).

``--compare`` matches programs across two runs (or two device counts)
and diffs predicted MFU, memory-bound share, predicted time, and
collective wire bytes. ``--min-attr`` exits 2 when any program
attributes less than the given fraction of its predicted device time to
named ops (the coverage gate, analogous to ``trace_report --min-attr``).
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple


def load_records(paths: List[str]) -> Tuple[Dict[str, dict], List[dict]]:
    """(latest roofline record per program, recompile records in order)."""
    rooflines: "OrderedDict[str, dict]" = OrderedDict()
    recompiles: List[dict] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                kind = rec.get("kind")
                if kind == "roofline" and rec.get("program"):
                    rooflines[str(rec["program"])] = rec
                elif kind == "recompile":
                    recompiles.append(rec)
    return rooflines, recompiles


def _eng(v: Optional[float], unit: str = "") -> str:
    if v is None:
        return "-"
    for scale, suf in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{suf}{unit}"
    return f"{v:.1f}{unit}"


def _ms(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.3f}ms"
    return f"{v * 1e6:.1f}us"


def _pct(v: Optional[float]) -> str:
    return "-" if v is None else f"{100.0 * v:.1f}%"


def _op_label(op: Dict[str, Any]) -> str:
    ins = ",".join(op.get("operands") or [])
    out = op.get("out") or ""
    return f"{op.get('op')}({ins})->{out}"


def print_program(rec: Dict[str, Any], top: int,
                  out=None) -> None:
    out = out if out is not None else sys.stdout
    name = rec["program"]
    static = rec.get("static_only")
    hdr = (f"== {name} — {rec.get('device_kind')} x"
           f"{rec.get('n_devices')}"
           + (" [STATIC-ONLY: no measured machine balance — time/MFU "
              "columns are a model]" if static else "") + " ==")
    print(hdr, file=out)
    print(f"  peak {rec.get('peak_tflops')} TF/s | hbm "
          f"{rec.get('hbm_gbps')} GB/s | balance "
          f"{rec.get('balance_flops_per_byte')} flops/byte", file=out)
    print(f"  predicted {_ms(rec.get('predicted_s'))}/execution | "
          f"predicted MFU {_pct(rec.get('predicted_mfu'))} | "
          f"flops {_eng(rec.get('total_flops'))} | "
          f"bytes {_eng(rec.get('total_bytes'), 'B')} | "
          f"attributed {_pct(rec.get('attributed_share'))}", file=out)
    unknown = max(0.0, 1.0 - (rec.get("memory_bound_share") or 0.0)
                  - (rec.get("compute_bound_share") or 0.0))
    print(f"  bound split: memory {_pct(rec.get('memory_bound_share'))} "
          f"| compute {_pct(rec.get('compute_bound_share'))} "
          f"| other {_pct(unknown)}", file=out)
    ops = rec.get("ops") or []
    if ops:
        print(f"\n  top {min(top, len(ops))} ops by predicted time:",
              file=out)
        print(f"  {'share':>6} {'time':>10} {'bound':<7} {'mult':>5} "
              f"{'flops':>9} {'bytes':>9} {'AI':>8}  op", file=out)
        for op in ops[:top]:
            ai = op.get("intensity")
            print(f"  {_pct(op.get('share')):>6} "
                  f"{_ms(op.get('time_s')):>10} "
                  f"{op.get('bound', '?'):<7} {op.get('mult', 1):>5} "
                  f"{_eng(op.get('flops')):>9} "
                  f"{_eng(op.get('bytes'), 'B'):>9} "
                  f"{ai if ai is not None else '-':>8}  "
                  f"{_op_label(op)}"
                  + (" [est]" if op.get("estimated") else ""), file=out)
    # per-operand-shape aggregation: the conv stream grouped by shape
    groups: "OrderedDict[str, Dict[str, float]]" = OrderedDict()
    for op in ops:
        if op.get("op") == "(other)":
            continue
        key = _op_label(op)
        g = groups.setdefault(key, {"share": 0.0, "time_s": 0.0,
                                    "count": 0, "bound": op.get("bound")})
        g["share"] += op.get("share") or 0.0
        g["time_s"] += op.get("time_s") or 0.0
        g["count"] += 1
    agg = sorted(groups.items(), key=lambda kv: kv[1]["share"],
                 reverse=True)
    if agg:
        print(f"\n  by operand shape (top {min(top, len(agg))}):",
              file=out)
        print(f"  {'share':>6} {'time':>10} {'bound':<7} {'n':>3}  "
              f"shape", file=out)
        for key, g in agg[:top]:
            print(f"  {_pct(g['share']):>6} {_ms(g['time_s']):>10} "
                  f"{g['bound'] or '?':<7} {g['count']:>3}  {key}",
                  file=out)
    colls = rec.get("collectives") or []
    if colls:
        print("\n  collectives (per device, per execution):", file=out)
        print(f"  {'op':<20} {'group':>5} {'count':>6} "
              f"{'payload':>10} {'wire bytes':>11}", file=out)
        for c in colls:
            print(f"  {c.get('op', '?'):<20} {c.get('group', '-'):>5} "
                  f"{c.get('count', 0):>6} "
                  f"{_eng(c.get('payload_bytes'), 'B'):>10} "
                  f"{_eng(c.get('wire_bytes'), 'B'):>11}", file=out)
        print(f"  total predicted collective wire bytes/execution: "
              f"{_eng(rec.get('collective_wire_bytes'), 'B')}", file=out)
    print("", file=out)


def print_recompiles(recompiles: List[dict], out=None) -> None:
    out = out if out is not None else sys.stdout
    if not recompiles:
        return
    print(f"recompile forensics ({len(recompiles)} event(s) past the "
          "pinned one-compile expectation):", file=out)
    for rec in recompiles:
        changed = rec.get("changed") or []
        if changed:
            det = "; ".join(
                f"{c.get('arg')}: {c.get('was')} -> {c.get('now')}"
                for c in changed[:6])
            if len(changed) > 6:
                det += f" (+{len(changed) - 6} more)"
        else:
            det = rec.get("note") or "no shape change recorded"
        print(f"  {rec.get('program')}: {rec.get('compiles')} compile(s) "
              f"(total {rec.get('total_compiles')}) — {det}", file=out)
    print("", file=out)


def print_compare(old: Dict[str, dict], new: Dict[str, dict],
                  out=None) -> None:
    """Programs present in only one capture are legitimate (a new fused
    kernel appears only in "after"; a host-path program disappears when a
    knob fuses it away) — they are reported as added/removed rows rather
    than silently dropped or KeyError'd."""
    out = out if out is not None else sys.stdout
    common = [p for p in old if p in new]
    added = [p for p in new if p not in old]
    removed = [p for p in old if p not in new]
    if not common and not added and not removed:
        print("no programs in either run", file=out)
        return
    print(f"{'program':<24} {'field':<26} {'old':>12} {'new':>12} "
          f"{'delta':>9}", file=out)
    fields = (("predicted_mfu", _pct), ("memory_bound_share", _pct),
              ("predicted_s", _ms), ("collective_wire_bytes",
                                     lambda v: _eng(v, "B")),
              ("total_flops", _eng), ("total_bytes",
                                      lambda v: _eng(v, "B")))
    for prog in common:
        o, n = old[prog], new[prog]
        for fname, fmt in fields:
            ov, nv = o.get(fname), n.get(fname)
            if ov is None and nv is None:
                continue
            if isinstance(ov, (int, float)) and isinstance(nv, (int, float)) \
                    and ov:
                delta = f"{100.0 * (nv - ov) / abs(ov):+.1f}%"
            else:
                delta = "-"
            print(f"{prog:<24} {fname:<26} {fmt(ov):>12} {fmt(nv):>12} "
                  f"{delta:>9}", file=out)
    for progs, rec_of, tag in ((added, new, "added"),
                               (removed, old, "removed")):
        for prog in progs:
            rec = rec_of[prog]
            for fname, fmt in fields:
                v = rec.get(fname)
                if v is None:
                    continue
                ov = "-" if tag == "added" else fmt(v)
                nv = fmt(v) if tag == "added" else "-"
                print(f"{prog:<24} {fname + ' [' + tag + ']':<26} "
                      f"{ov:>12} {nv:>12} {'-':>9}", file=out)
    if not common:
        print("(no common programs between the two runs)", file=out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("logs", nargs="+", help="run_<id>.jsonl file(s)")
    ap.add_argument("--top", type=int, default=12,
                    help="ops/shape-groups to print per program")
    ap.add_argument("--program", default=None,
                    help="only this program's record")
    ap.add_argument("--compare", default=None, metavar="OTHER",
                    help="second run log: diff predicted MFU / bound "
                    "share / collective bytes per program")
    ap.add_argument("--min-attr", type=float, default=0.0,
                    help="fail (exit 2) when any program attributes "
                    "less than this fraction of predicted time")
    args = ap.parse_args(argv)

    rooflines, recompiles = load_records(args.logs)
    if args.program:
        rooflines = {p: r for p, r in rooflines.items()
                     if p == args.program}
    if not rooflines and not recompiles:
        print("no roofline/recompile records found (capture with "
              "obs_roofline: true)", file=sys.stderr)
        return 1

    if args.compare:
        other, _ = load_records([args.compare])
        if args.program:
            other = {p: r for p, r in other.items() if p == args.program}
        print_compare(rooflines, other)
        return 0

    for rec in rooflines.values():
        print_program(rec, args.top)
    print_recompiles(recompiles)

    if args.min_attr > 0 and rooflines:
        worst_prog, worst = min(
            ((p, r.get("attributed_share") or 0.0)
             for p, r in rooflines.items()), key=lambda kv: kv[1])
        if worst < args.min_attr:
            print(f"FAIL: program {worst_prog!r} attributes only "
                  f"{100 * worst:.1f}% of its predicted device time "
                  f"(< {100 * args.min_attr:.0f}%)", file=sys.stderr)
            return 2
        print(f"coverage OK: every program attributes >= "
              f"{100 * args.min_attr:.0f}% (worst {worst_prog!r} at "
              f"{100 * worst:.1f}%)")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:   # `roofline_report ... | head` is fine
        sys.exit(0)
