#!/usr/bin/env python
"""Compare two bench result files and gate on regressions.

Pre-merge usage (documented in README "Benchmarks"): run ``bench.py``
before and after a change, then

    python scripts/bench_diff.py BENCH_r05.json /tmp/bench_new.json
    python scripts/bench_diff.py old.json new.json --threshold 0.05

Accepted file shapes (auto-detected per file):

* a ``BENCH_r*.json`` wrapper (``{"tail": "<bench stdout>"}``) — metric
  lines are parsed out of the captured stdout;
* raw ``bench.py`` stdout (one ``{"metric": ..., "value": ...}`` JSON
  object per line, non-JSON lines ignored);
* a single JSON object/array of such metric objects.

For every metric present in both files the tool prints the old/new
values and the delta; nested ``legs`` dicts (e.g. the serving sweep's
per-concurrency entries) are flattened to ``metric.leg.field`` rows.
Direction is inferred from the metric name — ``*_s`` / ``*seconds`` /
``*bytes*`` / ``*latency*`` are lower-is-better, everything else
(rounds/hour, tokens/s, MFU, accuracy) higher-is-better. Exits 1 when
any metric regresses past ``--threshold`` (relative), so a CI step can
gate merges on the bench trajectory.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

# matched against the LAST dotted component (the leg field for
# flattened rows); throughput-ish markers win over the `_s` suffix so
# "tokens_per_s" reads as higher-is-better while "p99_latency_s" and
# "time_to_90pct_s" read as lower-is-better. goodput/success cover the
# serving chaos leg; resets/trips/faults count recovery EPISODES —
# fewer is better (same plan, less damage). hit_rate/reused cover the
# prefix-cache leg (more prompt tokens served from cached KV is
# better); fragmentation/ttft are the gauges the cache must DRIVE DOWN
# (llm_ttft_seconds, llm_kv_fragmentation — ttft_* fields also end in
# `_s` and read lower-is-better via the suffix rule).
# roofline/weak-scaling additions (ISSUE 14): predicted MFU rides the
# existing `mfu` marker and collective wire bytes the `bytes` marker;
# `bound_share` covers roofline_memory_bound_share (drive the
# memory-bound time share DOWN), `efficiency` the weak-scaling column,
# `swaps` the adapter-churn leg's sustained hot-swap count (more churn
# absorbed at the same tokens/s is better).
# population-plane additions (ISSUE 15): `_ms` covers the cohort-
# assembly and strategy-select wall columns
# (cross_device_cohort_assembly_ms and its assembly_ms/select_*_ms
# legs), `overhead` the 1M-vs-10k scaling ratios — both drive DOWN
# (selection must stay sublinear in population).
# fused-kernel additions (ISSUE 16): fedavg_resnet56_fused_block_step_ms
# and its reference_ms/fused_ms legs ride the `_ms` marker (drive the
# fused step DOWN), its speedup leg the `speedup` marker (UP); the
# weak-scaling bench's new d{k}_int8 quantized-re-layout legs reuse
# `efficiency` (UP) and collective_wire_bytes_per_round's `bytes`
# marker (DOWN — the quantized all_to_all must shrink the wire).
# fleet-serving additions (ISSUE 17): llm_serving_fleet_tokens_per_s
# rides `per_s` (UP) and its ttft_mean_s/ttft_p99_s legs the `ttft`
# marker (DOWN); `hits` covers suffix_hits (UP — generated-token blocks
# aliased), `compiles` covers cold_start_compiles alongside the
# steady-state `recompiles` gauge (both DOWN), `scale_events` bounds
# the SLO autoscaler's move count (DOWN — a stable fleet does not
# staircase), `drops` the seeded chaos conn-drop count (DOWN).
# fleet-plane additions (ISSUE 18): the multi-tenant bench's headline
# rides `per_hour` (UP) and its assign_ms leg `_ms` (DOWN);
# `violations` covers fairness_violations and `overlap` the
# overlap_devices isolation column — both must stay pinned at 0, so any
# increase is a regression (DOWN).
# wire-pipeline additions (ISSUE 19): the cross-silo wire bench's
# secagg_compressed/gossip_compressed legs ride `bytes` (DOWN — the
# masked/N2N wire must stay shrunk); `reduction` covers their
# reduction_vs_* ratio columns (UP — HIGHER wins the probe before the
# `bytes` substring in reduction_vs_dense_field can read it DOWN) and
# `rounds_to` the rounds_to_target trajectory gates (DOWN — compression
# that costs convergence rounds is a regression, the ±2-round
# acceptance bound).
HIGHER_MARKERS = ("per_s", "per_hour", "mfu", "acc", "tokens", "speedup",
                  "goodput", "success", "hit_rate", "hits", "reused",
                  "efficiency", "swaps", "attributed", "reduction")
LOWER_MARKERS = ("seconds", "bytes", "latency", "recompiles", "compiles",
                 "time_to", "step_time", "wall", "round_s",
                 "resets", "trips", "faults", "fragmentation", "ttft",
                 "bound_share", "_ms", "overhead", "scale_events", "drops",
                 "violations", "overlap", "rounds_to")


def _wrapper_rc(path: str) -> Optional[int]:
    """The recorded exit code of a ``BENCH_r*.json`` wrapper, if any.
    A bench that crashed partway still leaves parseable metric lines in
    its tail — comparing only those would let the gate pass a change
    that broke the bench itself."""
    try:
        obj = json.loads(open(path).read())
    except ValueError:
        return None
    if isinstance(obj, dict) and "tail" in obj and "rc" in obj:
        try:
            return int(obj["rc"])
        except (TypeError, ValueError):
            return None
    return None


def _metric_objects(path: str) -> List[dict]:
    with open(path) as f:
        text = f.read()
    # wrapper file: {"tail": "<stdout>"} (the BENCH_r*.json layout)
    try:
        obj = json.loads(text)
        if isinstance(obj, dict) and "tail" in obj:
            text = obj["tail"]
        elif isinstance(obj, dict) and "metric" in obj:
            return [obj]
        elif isinstance(obj, list):
            return [o for o in obj
                    if isinstance(o, dict) and "metric" in o]
    except ValueError:
        pass
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            out.append(rec)
    return out


def flatten(path: str) -> Dict[str, float]:
    """File -> ``{row_name: value}``: the headline value per metric plus
    every numeric field of a nested ``legs`` dict."""
    rows: Dict[str, float] = {}
    for rec in _metric_objects(path):
        name = str(rec["metric"])
        v = rec.get("value")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            rows[name] = float(v)
        legs = rec.get("legs")
        if isinstance(legs, dict):
            for leg, ent in legs.items():
                if isinstance(ent, (int, float)) \
                        and not isinstance(ent, bool):
                    rows[f"{name}.{leg}"] = float(ent)
                elif isinstance(ent, dict):
                    for k, lv in ent.items():
                        if isinstance(lv, (int, float)) \
                                and not isinstance(lv, bool):
                            rows[f"{name}.{leg}.{k}"] = float(lv)
    return rows


def lower_is_better(name: str) -> bool:
    probe = name.rsplit(".", 1)[-1].lower()
    if any(m in probe for m in HIGHER_MARKERS):
        return False
    return probe.endswith("_s") \
        or any(m in probe for m in LOWER_MARKERS)


def diff(old: Dict[str, float], new: Dict[str, float],
         threshold: float, out=sys.stdout) -> int:
    common = sorted(set(old) & set(new))
    if not common:
        print("no common metrics between the two files", file=out)
        return 2
    hdr = (f"{'metric':<58} {'old':>12} {'new':>12} {'delta%':>8}  "
           f"verdict")
    print(hdr, file=out)
    print("-" * len(hdr), file=out)
    regressions: List[Tuple[str, float]] = []
    for name in common:
        o, n = old[name], new[name]
        if o == 0:
            rel = 0.0 if n == 0 else float("inf")
        else:
            rel = (n - o) / abs(o)
        lower = lower_is_better(name)
        regressed = rel > threshold if lower else rel < -threshold
        improved = rel < -threshold if lower else rel > threshold
        verdict = ("REGRESSED" if regressed
                   else "improved" if improved else "")
        if regressed:
            regressions.append((name, rel))
        print(f"{name:<58} {o:>12.4g} {n:>12.4g} {100 * rel:>7.1f}%  "
              f"{verdict}", file=out)
    only_old = sorted(set(old) - set(new))
    only_new = sorted(set(new) - set(old))
    if only_old:
        print(f"\nonly in old ({len(only_old)}): "
              + ", ".join(only_old[:8])
              + (" ..." if len(only_old) > 8 else ""), file=out)
    if only_new:
        print(f"only in new ({len(only_new)}): " + ", ".join(only_new[:8])
              + (" ..." if len(only_new) > 8 else ""), file=out)
    if regressions:
        print(f"\nFAIL: {len(regressions)} metric(s) regressed past "
              f"{100 * threshold:.0f}%:", file=out)
        for name, rel in regressions:
            print(f"  {name}: {100 * rel:+.1f}%", file=out)
        return 1
    print(f"\nOK: no regression past {100 * threshold:.0f}% across "
          f"{len(common)} compared metrics", file=out)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("old", help="baseline bench file (e.g. BENCH_r05.json)")
    ap.add_argument("new", help="candidate bench file")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative regression gate (default 0.10 = 10%%)")
    args = ap.parse_args(argv)
    rc_fail = 0
    for label, path in (("old", args.old), ("new", args.new)):
        rc = _wrapper_rc(path)
        if rc:
            print(f"FAIL: {label} bench file {path} records a non-zero "
                  f"bench exit code (rc={rc}) — its metrics are not "
                  "trustworthy", file=sys.stderr)
            rc_fail = 1
    verdict = diff(flatten(args.old), flatten(args.new), args.threshold)
    return verdict or rc_fail


if __name__ == "__main__":
    sys.exit(main())
