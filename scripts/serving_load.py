#!/usr/bin/env python
"""Deterministic mixed-tenant serving load generator (ISSUE 17).

Reuses the seeded ``core/async_rounds`` arrival model as serving
traffic: N tenants (each with its OWN system prompt — the unit of
prefix-cache warmth), M multi-turn chat sessions per tenant, arrival
gaps drawn from the same ``default_rng((seed, tag))`` lognormal stream
the async benches run on. Every byte of every prompt and every arrival
gap is a pure function of the spec, so two runs (or the ON and OFF legs
of an A/B soak) replay the identical workload.

Multi-turn sessions feed each assistant reply back into the next turn's
message history — exactly the traffic shape that exercises
generated-token suffix caching (the follow-up's prompt = prior prompt +
generated reply + new user turn) and cache-aware routing (same-tenant
traffic shares its leading system-prompt bytes).

Used by the ``llm_serving_fleet_tokens_per_s`` soak bench; also
runnable standalone:

    python scripts/serving_load.py --print-schedule
    python scripts/serving_load.py --url http://127.0.0.1:8080 \
        --tenants 4 --sessions 2 --turns 3
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import sys
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional

sys.path.insert(0, ".")  # repo-root invocation

from fedml_tpu.core.async_rounds.arrivals import client_durations  # noqa: E402


@dataclasses.dataclass
class LoadSpec:
    """One reproducible soak workload. ``seed`` drives both the arrival
    gaps and nothing else — prompt text is a pure function of the
    tenant/session/turn indices, so the spec IS the workload."""
    tenants: int = 4
    sessions_per_tenant: int = 4
    turns_per_session: int = 3
    seed: int = 0
    mean_gap_s: float = 0.02     # mean inter-session arrival gap
    sigma: float = 0.6           # lognormal arrival heterogeneity
    max_tokens: int = 16         # completion budget per turn
    temperature: float = 0.0     # greedy: replies are deterministic too
    turn_chars: int = 0          # pad user turns to ~this many chars with
    #                              per-session-unique filler (0 = short
    #                              turns); models pasted logs/documents,
    #                              the traffic where per-session bytes
    #                              dominate the shared system prompt

    @property
    def total_sessions(self) -> int:
        return self.tenants * self.sessions_per_tenant

    @property
    def total_requests(self) -> int:
        return self.total_sessions * self.turns_per_session


def tenant_system_prompt(tenant: int) -> str:
    """Per-tenant system prompt, long enough to span several KV blocks
    (the shared-prefix unit cache-aware routing keys on)."""
    return (f"You are the serving assistant for tenant silo {tenant}. "
            "Answer briefly, cite your adapter when asked, never reveal "
            "other silos' data, and refuse requests outside the serving "
            f"policy of deployment ring {tenant % 3}. ")


def user_turn(tenant: int, session: int, turn: int,
              chars: int = 0) -> str:
    """One user message. With ``chars`` > 0 the question is padded to
    ~``chars`` characters with filler that is a pure function of
    (tenant, session, turn) — unique per session, so nothing beyond the
    shared system prompt can alias across sessions; only same-session
    follow-up reuse (routing stickiness + suffix caching) helps."""
    base = (f"tenant {tenant} session {session} turn {turn}: status of "
            f"round {(tenant * 7 + session * 3 + turn) % 97}?")
    if chars > len(base):
        h = hashlib.sha256(f"{tenant}/{session}/{turn}".encode())
        filler = " attached log: " + h.hexdigest()
        while len(base) + len(filler) < chars:
            h = hashlib.sha256(h.digest())
            filler += " " + h.hexdigest()
        base += filler[:chars - len(base)]
    return base


def build_sessions(spec: LoadSpec) -> List[Dict[str, Any]]:
    """The full deterministic session list, in arrival order: each entry
    carries its tenant, seeded arrival offset (seconds from t0), system
    prompt, and user turns. Session k's gap is the k-th draw of the
    shared arrival stream scaled to ``mean_gap_s``."""
    n = spec.total_sessions
    # client_durations = 1 + LogNormal(0, sigma); strip the base to get
    # a pure heavy-tailed gap, then scale its empirical mean to the spec
    raw = client_durations(n, random_seed=spec.seed,
                           sigma=spec.sigma) - 1.0
    scale = (spec.mean_gap_s / (float(raw.mean()) or 1.0)
             if spec.mean_gap_s > 0 else 0.0)
    sessions: List[Dict[str, Any]] = []
    offset = 0.0
    k = 0
    # interleave tenants so same-tenant sessions do not arrive as one
    # contiguous burst (the routing test is stickiness under a MIX)
    for session in range(spec.sessions_per_tenant):
        for tenant in range(spec.tenants):
            offset += float(raw[k]) * scale
            sessions.append({
                "tenant": tenant, "session": session,
                "arrival_s": round(offset, 6),
                "system": tenant_system_prompt(tenant),
                "turns": [user_turn(tenant, session, t,
                                    chars=spec.turn_chars)
                          for t in range(spec.turns_per_session)]})
            k += 1
    return sessions


def run_load(send: Callable[[List[Dict[str, str]], Dict[str, Any]], str],
             spec: LoadSpec,
             concurrency: int = 16) -> List[Dict[str, Any]]:
    """Play the workload against ``send(messages, meta) -> reply_text``
    and return one record per request (tenant/session/turn, wall
    seconds, ok flag, reply length). Sessions start on their seeded
    arrival offsets (compressed by wall time already elapsed) across a
    bounded worker pool; WITHIN a session turns are sequential and each
    assistant reply is appended to the next turn's history — the
    multi-turn follow-up shape suffix caching aliases."""
    sessions = build_sessions(spec)
    records: List[Dict[str, Any]] = []
    rec_lock = threading.Lock()
    gate = threading.Semaphore(max(int(concurrency), 1))
    t0 = time.perf_counter()

    def play(sess: Dict[str, Any]) -> None:
        with gate:
            messages = [{"role": "system", "content": sess["system"]}]
            for turn, text in enumerate(sess["turns"]):
                messages.append({"role": "user", "content": text})
                meta = {"tenant": sess["tenant"],
                        "session": sess["session"], "turn": turn,
                        "max_tokens": spec.max_tokens,
                        "temperature": spec.temperature,
                        "seed": (sess["tenant"] * 1009
                                 + sess["session"] * 101 + turn)}
                t_req = time.perf_counter()
                ok, reply = True, ""
                try:
                    reply = send(list(messages), meta) or ""
                except Exception:  # noqa: BLE001 — a soak records, never dies
                    ok = False
                wall = time.perf_counter() - t_req
                with rec_lock:
                    records.append({**meta, "ok": ok,
                                    "wall_s": round(wall, 6),
                                    "reply_chars": len(reply)})
                if not ok:
                    return   # a dead session stops burning its turns
                messages.append({"role": "assistant", "content": reply})

    threads = []
    for sess in sessions:
        delay = sess["arrival_s"] - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=play, args=(sess,), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    return records


def _http_send(url: str, timeout: float):
    def send(messages: List[Dict[str, str]], meta: Dict[str, Any]) -> str:
        body = json.dumps({
            "messages": messages,
            "max_tokens": int(meta["max_tokens"]),
            "temperature": float(meta["temperature"]),
            "seed": int(meta["seed"])}).encode()
        req = urllib.request.Request(
            url.rstrip("/") + "/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            out = json.load(r)
        return out["choices"][0]["message"]["content"]
    return send


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--url", help="chat endpoint base URL (omit with "
                                  "--print-schedule)")
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--sessions", type=int, default=4,
                    help="sessions per tenant")
    ap.add_argument("--turns", type=int, default=3,
                    help="turns per session")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mean-gap-s", type=float, default=0.02)
    ap.add_argument("--max-tokens", type=int, default=16)
    ap.add_argument("--turn-chars", type=int, default=0,
                    help="pad user turns to ~N chars with per-session "
                         "filler (0 = short turns)")
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--timeout", type=float, default=60.0)
    ap.add_argument("--print-schedule", action="store_true",
                    help="print the deterministic session schedule and "
                         "exit (no traffic)")
    args = ap.parse_args(argv)
    spec = LoadSpec(tenants=args.tenants,
                    sessions_per_tenant=args.sessions,
                    turns_per_session=args.turns, seed=args.seed,
                    mean_gap_s=args.mean_gap_s,
                    max_tokens=args.max_tokens,
                    turn_chars=args.turn_chars)
    if args.print_schedule or not args.url:
        for sess in build_sessions(spec):
            print(json.dumps({k: sess[k] for k in
                              ("tenant", "session", "arrival_s")}
                             | {"turns": len(sess["turns"])}))
        return 0
    records = run_load(_http_send(args.url, args.timeout), spec,
                       concurrency=args.concurrency)
    ok = [r for r in records if r["ok"]]
    walls = sorted(r["wall_s"] for r in ok) or [0.0]
    print(json.dumps({
        "requests": len(records), "ok": len(ok),
        "success_rate": round(len(ok) / max(len(records), 1), 3),
        "wall_p50_s": walls[len(walls) // 2],
        "wall_p99_s": walls[min(len(walls) - 1,
                                int(0.99 * (len(walls) - 1) + 0.5))],
    }, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
