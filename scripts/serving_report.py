#!/usr/bin/env python
"""Serving-plane flight report: per-request waterfalls + SLO percentiles.

The serving sibling of ``trace_report.py``: reads run JSONLs (the mlops
sink's ``run_<id>.jsonl`` — pass the replica's file, or every process's
for a gateway session; spans carry trace/span IDs so trees reassemble
across files), rebuilds each ``serving.request`` trace, and prints

* one waterfall row per request — wall time split into queue wait /
  chunked prefill / decode (the engine's ``serving.queue`` /
  ``serving.prefill`` / ``serving.decode`` child spans), TTFT,
  per-request tokens/s, finish reason, and the attributed fraction
  (the ≥95% acceptance bar: unattributed time is wall no span explains);
* a TTFT/ITL/queue-wait percentile table — TTFT and queue wait exact
  from the request spans, inter-token latency from the last
  ``metrics_snapshot``'s ``llm_inter_token_seconds`` histogram
  (linear interpolation within buckets).

    python scripts/serving_report.py ~/.cache/fedml_tpu/logs/run_0.jsonl
    python scripts/serving_report.py run.jsonl --min-attr 0.95
    python scripts/serving_report.py run.jsonl --trace 4f2a
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

# request-lifecycle phases, in waterfall order (keep in sync with
# fedml_tpu/core/obs/schema.py SERVING_SPAN_NAMES)
PHASES = ("serving.queue", "serving.prefill", "serving.decode")


def load_records(paths: List[str]) -> Tuple[List[dict], List[dict]]:
    spans, snapshots = [], []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                kind = rec.get("kind")
                if kind == "span":
                    spans.append(rec)
                elif kind == "metrics_snapshot":
                    snapshots.append(rec)
    return spans, snapshots


def union_len(intervals: List[Tuple[float, float]]) -> float:
    total, end = 0.0, -float("inf")
    for s, e in sorted(intervals):
        if e <= end:
            continue
        total += e - max(s, end)
        end = e
    return total


def exact_pct(values: List[float], q: float) -> float:
    """Nearest-rank percentile over raw values."""
    vs = sorted(values)
    return vs[min(len(vs) - 1, int(q * (len(vs) - 1) + 0.5))]


def hist_pct(buckets: List[float], counts: List[int], q: float
             ) -> Optional[float]:
    """Approximate percentile from per-bucket counts (len(buckets)+1,
    +Inf last) by linear interpolation inside the winning bucket."""
    total = sum(counts)
    if not total:
        return None
    target = q * total
    cum = 0.0
    lo = 0.0
    for i, c in enumerate(counts):
        hi = buckets[i] if i < len(buckets) else buckets[-1]
        if cum + c >= target and c > 0:
            frac = (target - cum) / c
            return lo + frac * (hi - lo)
        cum += c
        lo = hi
    return buckets[-1]


def analyze_request(root: dict, children: List[dict]) -> Dict[str, Any]:
    lo, hi = float(root["start_ts"]), float(root["end_ts"])
    wall = max(hi - lo, 1e-12)
    phase_s: Dict[str, float] = {}
    covered: List[Tuple[float, float]] = []
    cached = novel = wave = None
    for c in children:
        s = max(float(c["start_ts"]), lo)
        e = min(float(c["end_ts"]), hi)
        if e <= s:
            continue
        covered.append((s, e))
        phase_s[c["name"]] = phase_s.get(c["name"], 0.0) + (e - s)
        if c["name"] == "serving.prefill":
            # prefix-cache + piggybacked-prefill annotations (the FIRST
            # admission's numbers; a requeued recompute overwrites them
            # with its own, which is the admission that last ran)
            ca = c.get("attrs", {}) or {}
            if "cached_tokens" in ca:
                cached = ca.get("cached_tokens")
                novel = ca.get("novel_tokens")
            if "wave" in ca:
                wave = ca.get("wave")
    attrs = root.get("attrs", {}) or {}
    return {
        "trace_id": root["trace_id"],
        "wall_s": wall,
        "phases": phase_s,
        "attributed_frac": min(union_len(covered) / wall, 1.0),
        "prompt_tokens": attrs.get("prompt_tokens"),
        "completion_tokens": attrs.get("completion_tokens"),
        "finish_reason": attrs.get("finish_reason",
                                   attrs.get("error", "?")),
        "ttft_s": attrs.get("ttft_s"),
        "queue_wait_s": attrs.get("queue_wait_s"),
        "tokens_per_s": attrs.get("tokens_per_s"),
        "cached_tokens": cached,
        "novel_tokens": novel,
        "wave": wave,
    }


def last_itl_histogram(snapshots: List[dict]
                       ) -> Optional[Tuple[List[float], List[int]]]:
    for snap in reversed(snapshots):
        inst = (snap.get("metrics") or {}).get("llm_inter_token_seconds")
        if inst and inst.get("values"):
            v = inst["values"][0]
            return list(v["buckets"]), list(v["counts"])
    return None


def print_report(spans: List[dict], snapshots: List[dict],
                 only_trace: Optional[str], min_attr: float,
                 out=sys.stdout) -> int:
    by_parent: Dict[str, List[dict]] = defaultdict(list)
    for s in spans:
        if s.get("parent_id"):
            by_parent[s["parent_id"]].append(s)
    requests = [s for s in spans
                if s.get("name") == "serving.request"
                and (only_trace is None
                     or s["trace_id"].startswith(only_trace))]
    if not requests:
        print("no serving.request spans found", file=out)
        return 1
    requests.sort(key=lambda s: s["start_ts"])
    rows = [analyze_request(r, by_parent.get(r["span_id"], []))
            for r in requests]

    hdr = (f"{'request':<22} {'wall_s':>8} {'queue':>8} {'prefill':>8} "
           f"{'decode':>8} {'ttft_s':>7} {'tok/s':>7} {'cache':>9} "
           f"{'wave':>5} {'finish':>8} {'attr%':>6}  trace")
    print(hdr, file=out)
    print("-" * len(hdr), file=out)
    worst = 1.0
    for a in rows:
        worst = min(worst, a["attributed_frac"])
        p = a["phases"]
        label = (f"{a['prompt_tokens'] or '?'}tok"
                 f"->{a['completion_tokens'] if a['completion_tokens'] is not None else '?'}tok")
        ttft = a["ttft_s"]
        tps = a["tokens_per_s"]
        # prefix-cache annotation: tokens reused from resident KV blocks
        # vs tokens actually prefilled; wave = piggybacked-prefill batch
        # membership (rows sharing a wave id admitted in one pass)
        if a["cached_tokens"] is None:
            cache = "-"
        else:
            total = int(a["cached_tokens"]) + int(a["novel_tokens"] or 0)
            cache = f"{a['cached_tokens']}/{total}"
        wave = f"w{a['wave']}" if a["wave"] is not None else "-"
        print(f"{label:<22} {a['wall_s']:>8.4f} "
              f"{p.get('serving.queue', 0.0):>8.4f} "
              f"{p.get('serving.prefill', 0.0):>8.4f} "
              f"{p.get('serving.decode', 0.0):>8.4f} "
              f"{ttft if ttft is not None else float('nan'):>7.3f} "
              f"{tps if tps is not None else float('nan'):>7.1f} "
              f"{cache:>9} {wave:>5} "
              f"{str(a['finish_reason']):>8} "
              f"{100.0 * a['attributed_frac']:>5.1f}%  "
              f"{a['trace_id'][:12]}", file=out)

    # --- SLO percentile table ------------------------------------------
    print(file=out)
    ttfts = [a["ttft_s"] for a in rows if a["ttft_s"] is not None]
    waits = [a["queue_wait_s"] for a in rows
             if a["queue_wait_s"] is not None]
    walls = [a["wall_s"] for a in rows]
    qs = (0.50, 0.90, 0.99)
    header = f"{'SLO':<26} " + " ".join(f"p{int(q * 100):>2}".rjust(9)
                                        for q in qs)
    print(header, file=out)
    print("-" * len(header), file=out)

    def slo_row(name: str, vals: Optional[List[float]],
                approx: bool = False) -> None:
        if not vals:
            print(f"{name:<26} " + " ".join(["      n/a"] * len(qs)),
                  file=out)
            return
        cells = " ".join(f"{exact_pct(vals, q):>9.4f}" for q in qs)
        print(f"{name:<26}{'~' if approx else ' '}{cells}", file=out)

    slo_row("ttft_s (exact, spans)", ttfts)
    slo_row("queue_wait_s (exact)", waits)
    slo_row("request_wall_s (exact)", walls)
    itl = last_itl_histogram(snapshots)
    if itl is not None:
        buckets, counts = itl
        cells = []
        for q in qs:
            v = hist_pct(buckets, counts, q)
            cells.append(f"{v:>9.5f}" if v is not None else "      n/a")
        print(f"{'itl_s (histogram)':<26}~" + " ".join(cells), file=out)
    else:
        print(f"{'itl_s (histogram)':<26}  no metrics_snapshot with "
              "llm_inter_token_seconds", file=out)

    n = len(rows)
    mean_attr = sum(a["attributed_frac"] for a in rows) / n
    print(f"\n{n} requests; attribution mean {100 * mean_attr:.1f}%, "
          f"min {100 * worst:.1f}%", file=out)
    if min_attr > 0 and worst < min_attr:
        print(f"FAIL: minimum attribution {100 * worst:.1f}% < "
              f"{100 * min_attr:.0f}% — request wall no span explains",
              file=out)
        return 2
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("logs", nargs="+",
                    help="run JSONL file(s) — pass every process's log")
    ap.add_argument("--trace", default=None,
                    help="only requests in this trace id (prefix match)")
    ap.add_argument("--min-attr", type=float, default=0.0,
                    help="exit 2 if any request's attributed fraction "
                         "is below this (e.g. 0.95)")
    args = ap.parse_args(argv)
    spans, snapshots = load_records(args.logs)
    return print_report(spans, snapshots, args.trace, args.min_attr)


if __name__ == "__main__":
    sys.exit(main())
