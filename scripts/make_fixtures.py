"""Generate the tiny checked-in dataset-format fixtures under
``tests/fixtures/`` — files in the EXACT on-disk layout of the reference's
TFF HDF5 datasets (fed_cifar100, stackoverflow NWP/LR), small enough to
commit (a few KB) but structurally faithful so the readers in
``fedml_tpu/data/tff_h5.py`` are pinned to the real format.

Deterministic: re-running reproduces byte-identical content modulo HDF5
metadata.
"""

from __future__ import annotations

import json
import os

import h5py
import numpy as np

ROOT = os.path.join(os.path.dirname(__file__), "..", "tests", "fixtures")

WORDS = ("the to how do in a i is of and python file java use using get "
         "from code for data can if with on error not you this my it "
         "function").split()
TAGS = ["python", "java", "javascript", "android", "c#", "php", "jquery",
        "html"]


def fed_cifar100(dirpath: str) -> None:
    os.makedirs(dirpath, exist_ok=True)
    rng = np.random.RandomState(0)
    for split, n_clients, n_img in (("train", 4, 12), ("test", 2, 8)):
        path = os.path.join(dirpath, f"fed_cifar100_{split}.h5")
        with h5py.File(path, "w") as f:
            ex = f.create_group("examples")
            for c in range(n_clients):
                g = ex.create_group(f"client_{c:02d}")
                g.create_dataset(
                    "image", data=rng.randint(0, 256, (n_img, 32, 32, 3),
                                              np.uint8))
                g.create_dataset(
                    "label", data=rng.randint(0, 100, (n_img, 1), np.int64))


def _sentences(rng, n):
    return [" ".join(rng.choice(WORDS, rng.randint(3, 12)))
            for _ in range(n)]


def stackoverflow(dirpath: str) -> None:
    os.makedirs(dirpath, exist_ok=True)
    rng = np.random.RandomState(1)
    for split, n_clients, n_rows in (("train", 4, 10), ("test", 2, 6)):
        path = os.path.join(dirpath, f"stackoverflow_{split}.h5")
        with h5py.File(path, "w") as f:
            ex = f.create_group("examples")
            for c in range(n_clients):
                g = ex.create_group(f"user_{c:02d}")
                sents = _sentences(rng, n_rows)
                tags = ["|".join(rng.choice(TAGS, rng.randint(1, 3),
                                            replace=False))
                        for _ in range(n_rows)]
                st = h5py.string_dtype()
                g.create_dataset("tokens", data=sents, dtype=st)
                g.create_dataset("title", data=sents, dtype=st)
                g.create_dataset("tags", data=tags, dtype=st)
    # vocab: word + count, most frequent first (reference word_count format)
    with open(os.path.join(dirpath, "stackoverflow.word_count"), "w") as f:
        for i, w in enumerate(WORDS):
            f.write(f"{w} {1000 - i}\n")
    # tags: json ordered dict tag -> count
    with open(os.path.join(dirpath, "stackoverflow.tag_count"), "w") as f:
        json.dump({t: 500 - i for i, t in enumerate(TAGS)}, f)


def main() -> None:
    fed_cifar100(os.path.join(ROOT, "fed_cifar100"))
    stackoverflow(os.path.join(ROOT, "stackoverflow_nwp"))
    stackoverflow(os.path.join(ROOT, "stackoverflow_lr"))
    print("fixtures written under", os.path.abspath(ROOT))


if __name__ == "__main__":
    main()
