"""Quantify the fused-dispatch advantage robust mode forfeits
(VERDICT r4 item 10, option b).

Robust/defended rounds run per-round by design: the collect -> defend ->
server-update pipeline crosses the host between jitted stages (ordering
rows by sampled ids, attacker masks, contribution bookkeeping), so the
multi-round ``lax.scan`` fusion (one dispatch per 8 rounds) cannot wrap
it. This script measures what that costs on the flagship shape, printing
three legs:

  fused          run_rounds_fused, 8 rounds/dispatch (production default)
  per_round      same engine, no defense, one dispatch per round
  defended       multi-krum defense on (robust collect path), per round

Defended overhead = defended - per_round (defense compute + collect
path); forfeited fusion = per_round - fused (the dispatch amortization).
Results are recorded in BASELINE.md §"Robust-mode dispatch cost".

ISSUE 2 superseded the "cannot wrap it" premise for sharded-capable
defenses (``robust_fused``): the defended legs here pin
``robust_fused: host`` to keep measuring the legacy pipeline, and a
fourth leg (``defended_fused_round_s``) measures the fused default.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def measure(n_clients=16, rounds_per_leg=8):
    import jax
    import jax.numpy as jnp

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    def args_for(defended: bool, robust_fused: str = "host"):
        kw = dict(
            dataset="cifar10", model="resnet56", precision="bfloat16",
            client_num_in_total=n_clients, client_num_per_round=n_clients,
            comm_round=rounds_per_leg, epochs=1, batch_size=32,
            learning_rate=0.1, frequency_of_the_test=-1, random_seed=0,
            allow_synthetic=True, synthetic_size=4000,
            max_total_samples=4000)
        if defended:
            # pin the HOST pipeline: this script quantifies what the
            # pre-ISSUE-2 per-round robust path costs; the fused robust
            # leg below measures the default (robust_fused: auto) instead
            kw.update(enable_defense=True, defense_type="multi_krum",
                      byzantine_client_num=2, krum_param_m=4,
                      robust_fused=robust_fused)
        return Arguments(**kw)

    def force(sim):
        return float(jax.tree_util.tree_leaves(sim.params)[0].sum())

    def build(defended: bool, robust_fused: str = "host"):
        a = args_for(defended, robust_fused)
        fed, output_dim = load(a)
        bundle = create(a, output_dim)
        spec = ClassificationTrainer(bundle.apply)
        return a, TPUSimulator(a, fed, bundle, create_optimizer(a, spec),
                               spec)

    hyper = TrainHyper(learning_rate=jnp.float32(0.1), epochs=1)
    out = {}

    # fused (8 rounds per dispatch)
    _, sim = build(False)
    sim.run_rounds_fused(0, rounds_per_leg, hyper)
    force(sim)
    t0 = time.perf_counter()
    sim.run_rounds_fused(rounds_per_leg, rounds_per_leg, hyper)
    force(sim)
    out["fused_round_s"] = (time.perf_counter() - t0) / rounds_per_leg

    # per-round, undefended
    _, sim = build(False)
    sim.run_round(0, hyper)
    force(sim)
    t0 = time.perf_counter()
    for r in range(1, rounds_per_leg + 1):
        sim.run_round(r, hyper)
    force(sim)
    out["per_round_s"] = (time.perf_counter() - t0) / rounds_per_leg

    # per-round, defended (robust collect path + multi-krum)
    _, sim = build(True)
    sim.run_round(0, hyper)
    force(sim)
    t0 = time.perf_counter()
    for r in range(1, rounds_per_leg + 1):
        sim.run_round(r, hyper)
    force(sim)
    out["defended_round_s"] = (time.perf_counter() - t0) / rounds_per_leg

    # fused robust (ISSUE 2 default: whole defended round as one program,
    # scanned 8 rounds per dispatch)
    _, sim = build(True, robust_fused="auto")
    assert sim.robust_fused, "multi_krum should take the fused path"
    sim.run_rounds_fused(0, rounds_per_leg, hyper)
    force(sim)
    t0 = time.perf_counter()
    sim.run_rounds_fused(rounds_per_leg, rounds_per_leg, hyper)
    force(sim)
    out["defended_fused_round_s"] = ((time.perf_counter() - t0)
                                     / rounds_per_leg)

    out["forfeited_fusion_s"] = out["per_round_s"] - out["fused_round_s"]
    out["defense_overhead_s"] = (out["defended_round_s"]
                                 - out["per_round_s"])
    out["defended_vs_fused"] = out["defended_round_s"] / out["fused_round_s"]
    out["defended_fused_vs_host"] = (out["defended_round_s"]
                                     / out["defended_fused_round_s"])
    return out


if __name__ == "__main__":
    print(json.dumps({k: round(v, 4) for k, v in measure().items()},
                     indent=2))
