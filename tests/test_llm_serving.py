"""LLM serving template (VERDICT r4 item 8): causal-LM predictor with a
compiled generate loop behind the inference runner, an OpenAI-compatible
/v1/chat/completions route, and the autoscaler driving LLM replicas."""

import json
import urllib.request

import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.llm.federated import build_llm
from fedml_tpu.serving import save_model
from fedml_tpu.serving.llm_template import (CausalLMPredictor,
                                            ChatCompletionRunner,
                                            serve_chat)

pytestmark = pytest.mark.slow


def _args(**kw):
    base = dict(dataset="llm_synthetic", model="causal_lm",
                client_num_in_total=2, client_num_per_round=2,
                comm_round=1, epochs=1, batch_size=4, learning_rate=1e-3,
                random_seed=3, llm_hidden_size=32, llm_num_layers=1,
                llm_num_heads=2, llm_intermediate_size=64,
                llm_max_seq_len=64, lora_rank=4)
    base.update(kw)
    return Arguments(**base)


@pytest.fixture(scope="module")
def served():
    args = _args()
    _, bundle, _, tokenizer = build_llm(args)
    import jax
    params = bundle.init(jax.random.PRNGKey(0),
                         np.zeros((1, 8), np.int32))
    predictor = CausalLMPredictor(bundle, params, tokenizer=tokenizer)
    return args, bundle, params, tokenizer, predictor


class TestGenerate:
    def test_greedy_is_deterministic_and_bounded(self, served):
        _, _, _, _, predictor = served
        a = predictor.generate("add 2 3", max_new_tokens=8)
        b = predictor.generate("add 2 3", max_new_tokens=8)
        assert a["text"] == b["text"]  # temp=0 -> greedy -> deterministic
        assert a["completion_tokens"] <= 8
        assert a["finish_reason"] in ("stop", "length")

    def test_temperature_sampling_uses_seed(self, served):
        _, _, _, _, predictor = served
        a = predictor.generate("echo", max_new_tokens=8, temperature=1.5,
                               seed=1)
        b = predictor.generate("echo", max_new_tokens=8, temperature=1.5,
                               seed=1)
        assert a["text"] == b["text"]  # same seed -> same sample path

    def test_artifact_round_trip_preserves_generation(self, served, tmp_path):
        args, bundle, params, tokenizer, predictor = served
        path = save_model(params, str(tmp_path / "lm.fmtpu"))
        loaded = CausalLMPredictor.from_artifact(args, path)
        assert (loaded.generate("add 1 1", max_new_tokens=6)["text"]
                == predictor.generate("add 1 1", max_new_tokens=6)["text"])


class TestChatEndpoint:
    def _post(self, port, path, payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.load(r)

    def test_openai_chat_completions_schema(self, served):
        _, _, _, _, predictor = served
        runner = ChatCompletionRunner(predictor)
        port = runner.start()
        try:
            out = self._post(port, "/v1/chat/completions", {
                "model": "fedml-tpu-lm",
                "messages": [{"role": "user", "content": "add 2 3"}],
                "max_tokens": 8})
            assert out["object"] == "chat.completion"
            assert out["choices"][0]["message"]["role"] == "assistant"
            assert isinstance(out["choices"][0]["message"]["content"], str)
            assert out["choices"][0]["finish_reason"] in ("stop", "length")
            usage = out["usage"]
            assert usage["total_tokens"] == (usage["prompt_tokens"]
                                             + usage["completion_tokens"])
            # the plain /predict surface stays mounted on the same server
            plain = self._post(port, "/predict",
                               {"prompt": "add 2 3", "max_new_tokens": 4})
            assert "text" in plain
        finally:
            runner.stop()

    def test_serve_chat_from_artifact(self, served, tmp_path):
        args, _, params, _, _ = served
        path = save_model(params, str(tmp_path / "lm2.fmtpu"))
        runner = serve_chat(args, path)
        try:
            out = self._post(runner.port, "/v1/chat/completions", {
                "messages": [{"role": "user", "content": "echo hi"}],
                "max_tokens": 4})
            assert out["object"] == "chat.completion"
        finally:
            runner.stop()


def test_autoscaler_drives_llm_replicas(served):
    """The autoscaler's ReplicaSet/Gateway serve chat completions when
    replicas mount the LLM template's routes."""
    from fedml_tpu.serving.autoscale import Gateway, ReplicaSet
    _, bundle, params, tokenizer, _ = served
    rs = ReplicaSet(
        predictor_factory=lambda: CausalLMPredictor(
            bundle, params, tokenizer=tokenizer),
        min_replicas=1, max_replicas=2,
        runner_cls=ChatCompletionRunner)
    gw = Gateway(rs, window_s=2.0)
    try:
        out = gw.predict({
            "messages": [{"role": "user", "content": "add 4 5"}],
            "max_tokens": 4}, path="/v1/chat/completions")
        assert out["object"] == "chat.completion"
        # scaling up keeps serving chat on every replica
        rs.scale_to(2)
        outs = [gw.predict({"messages": [{"role": "user",
                                          "content": "echo x"}],
                            "max_tokens": 4},
                           path="/v1/chat/completions")
                for _ in range(4)]
        assert all(o["object"] == "chat.completion" for o in outs)
    finally:
        rs.stop()
