"""Self-healing LLM serving (ISSUE 11): watchdog-driven engine recovery,
preempt-and-requeue backpressure, load shedding, health-aware gateway
failover, and the seeded serving chaos plane.

Quick gate: the recovery/requeue/shed/failover mechanics on stub
schedulers + the recovery-determinism pin on the real tiny model. Slow:
the c8 crash+stall+NaN chaos soak (every request completes, ledger
balanced, compile-once) and the subprocess replica-crash path.
"""

import concurrent.futures as cf
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core.chaos import (FaultLedger, FaultPlan,
                                  ServingChaosInjector)
from fedml_tpu.core.obs import metrics as obs_metrics
from fedml_tpu.llm.federated import build_llm
from fedml_tpu.serving import FedMLInferenceRunner, Overloaded
from fedml_tpu.serving.batch.engine import BatchingEngine
from fedml_tpu.serving.llm_template import (CausalLMPredictor,
                                            ChatCompletionRunner)

pytestmark = [pytest.mark.serving, pytest.mark.chaos]


def _args(**kw):
    base = dict(dataset="llm_synthetic", model="causal_lm",
                client_num_in_total=2, client_num_per_round=2,
                comm_round=1, epochs=1, batch_size=4, learning_rate=1e-3,
                random_seed=3, llm_hidden_size=32, llm_num_layers=2,
                llm_num_heads=2, llm_intermediate_size=64,
                llm_max_seq_len=64, lora_rank=4)
    base.update(kw)
    return Arguments(**base)


@pytest.fixture(scope="module")
def lora_setup():
    import jax
    args = _args()
    _, bundle, _, tok = build_llm(args)
    params = bundle.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))
    return args, bundle, params, tok


# ---------------------------------------------------------------- stubs ----

class _FakeScheduler:
    """Deterministic in-memory scheduler: token t for (seed, position) is
    a pure function, exactly like the real stateless sampler — so the
    requeue/recompute path can be exercised without a compile. Faults are
    driven by flipping ``poison_next``."""

    EOS_NEVER = True

    def __init__(self, slots=2, max_seq_len=100000, num_blocks=1024,
                 step_delay=0.0):
        from types import SimpleNamespace
        self.cfg = SimpleNamespace(max_seq_len=max_seq_len)
        self.cache_cfg = SimpleNamespace(
            num_blocks=num_blocks, max_seq_len=max_seq_len,
            blocks_needed=lambda n: max(1, (n + 15) // 16))
        self.slots = slots
        self.step_delay = float(step_delay)
        self._slots = {}       # slot -> dict(ids, pos, seed)
        self.steps_run = 0
        self.resets = 0
        self.last_step_finite = True
        self.poison_next = 0   # poison this many upcoming steps
        self.step_barrier = None   # optional Event: block steps

    # admission ----------------------------------------------------------
    def can_admit(self, prompt_len, max_new):
        return len(self._slots) < self.slots

    def admit(self, ids, *, adapter_idx=0, temperature=0.0, seed=0,
              max_new_tokens=64):
        slot = min(s for s in range(self.slots) if s not in self._slots)
        self._slots[slot] = {"ids": list(ids), "pos": len(ids),
                             "seed": int(seed)}
        return slot, self._token(int(seed), len(ids))

    def release(self, slot):
        self._slots.pop(slot, None)

    @staticmethod
    def _token(seed, position):
        from fedml_tpu.llm.data import EOS
        tok = (seed * 31 + position * 7) % 200 + EOS + 1
        return tok

    # stepping -----------------------------------------------------------
    def step(self):
        if self.step_barrier is not None:
            self.step_barrier.wait(timeout=30)
        if self.step_delay:
            time.sleep(self.step_delay)
        self.steps_run += 1
        if self.poison_next > 0:
            self.poison_next -= 1
            self.last_step_finite = False
            return {}
        out = {}
        for slot, st in self._slots.items():
            st["pos"] += 1
            out[slot] = self._token(st["seed"], st["pos"])
        return out

    def active_count(self):
        return len(self._slots)

    def slot_position(self, slot):
        return self._slots[slot]["pos"]

    def reset(self):
        self._slots.clear()
        self.last_step_finite = True
        self.resets += 1

    def kv_pool_stats(self):
        return {"used_blocks": len(self._slots), "free_blocks": 8,
                "headroom_requests": max(self.slots - len(self._slots), 1),
                "fragmentation": 0.0}

    def debug_state(self):
        return {"slots": sorted(self._slots), "kv_pool":
                self.kv_pool_stats()}


def _drain(fut, timeout=30):
    return fut.result(timeout=timeout)


# -------------------------------------------------- engine recovery ----

class TestEngineRecovery:
    def test_nan_step_triggers_reset_and_requests_complete(self):
        """A poisoned step (NaN logits) triggers a controlled reset: the
        scheduler is rebuilt, in-flight requests are requeued, and they
        finish with the same tokens an undisturbed run produces."""
        sched = _FakeScheduler(slots=2)
        eng = BatchingEngine(sched, watchdog_s=0.0, max_resets=3,
                             max_requeues=2)
        try:
            ref_sched = _FakeScheduler(slots=2)
            ref = BatchingEngine(ref_sched, watchdog_s=0.0)
            a = _drain(ref.submit([5, 6, 7], max_new_tokens=8, seed=11))
            ref.stop()

            sched.poison_next = 1   # the first step emits garbage
            fut = eng.submit([5, 6, 7], max_new_tokens=8, seed=11)
            out = _drain(fut)
            assert out["finish_reason"] == "length"
            assert out["ids"] == a["ids"]        # bit-identical replay
            assert sched.resets == 1
            assert eng.resets_total == 1
            assert eng.health()["status"] == "ok"   # recovered
        finally:
            eng.stop()

    def test_reset_budget_exhausted_parks_unhealthy(self, tmp_path):
        """Persistent poison exhausts the reset budget: survivors resolve
        "preempted", /healthz goes (and stays) non-ok, the flight ring is
        dumped, and new submits are rejected."""
        sched = _FakeScheduler(slots=2)
        eng = BatchingEngine(sched, watchdog_s=0.0, max_resets=2,
                             max_requeues=10, flight_dir=str(tmp_path))
        try:
            sched.poison_next = 10 ** 6   # poison every step forever
            fut = eng.submit([5, 6, 7], max_new_tokens=8)
            out = _drain(fut)
            assert out["finish_reason"] == "preempted"
            deadline = time.time() + 5
            while time.time() < deadline \
                    and eng.health()["status"] != "failed":
                time.sleep(0.02)
            h = eng.health()
            assert h["status"] == "failed"
            assert h["failed_reason"] == "nan_logits"
            assert h["reset_budget_remaining"] == 0
            with pytest.raises(RuntimeError, match="unhealthy"):
                eng.submit([1, 2, 3], max_new_tokens=4)
            dumps = [p for p in os.listdir(str(tmp_path))
                     if p.startswith("flight_serving_engine")]
            assert dumps, "give-up never dumped the flight ring"
        finally:
            eng.stop()

    def test_requeue_exhausted_resolves_preempted_with_prefix(self):
        """A request that keeps getting caught in resets past its requeue
        budget resolves "preempted" with the tokens it has, not an
        exception and not a silent "length"."""
        sched = _FakeScheduler(slots=1, step_delay=0.005)
        eng = BatchingEngine(sched, watchdog_s=0.0, max_resets=10,
                             max_requeues=1)
        try:
            fut = eng.submit([5, 6], max_new_tokens=200)
            time.sleep(0.1)       # let some tokens land
            sched.poison_next = 1
            time.sleep(0.2)       # reset 1: requeue (budget 1)
            sched.poison_next = 1
            out = _drain(fut)
            assert out["finish_reason"] == "preempted"
            assert out["completion_tokens"] < 200
        finally:
            eng.stop()

    def test_injected_stall_recovers_via_watchdog(self, tmp_path):
        """The watchdog-driven path end to end: a chaos-injected decode
        stall stops progress, the watchdog trips, the trip requests a
        reset, and the stalled request completes after recompute."""
        plan = FaultPlan(seed=7, serving_stall_at_step=3,
                         serving_stall_s=30.0)
        ledger = FaultLedger()
        inj = ServingChaosInjector(plan, ledger=ledger)
        sched = _FakeScheduler(slots=2)
        eng = BatchingEngine(sched, watchdog_s=0.3, max_resets=3,
                             flight_dir=str(tmp_path), chaos=inj)
        try:
            fut = eng.submit([5, 6, 7], max_new_tokens=12, seed=4)
            out = _drain(fut, timeout=20)
            assert out["finish_reason"] == "length"
            assert out["completion_tokens"] == 12
            assert eng.resets_total >= 1
            assert eng.watchdog.trips >= 1
            kinds = [e["kind"] for e in ledger.serving_events()]
            assert "stall" in kinds          # injected-vs-observed
            assert eng.health()["status"] == "ok"
        finally:
            eng.stop()

    def test_flight_dumps_never_overwrite(self, tmp_path):
        """Satellite: two recovery episodes in one process leave TWO
        post-mortem files (monotonic suffix), not one overwritten."""
        sched = _FakeScheduler(slots=1)
        eng = BatchingEngine(sched, watchdog_s=0.0, max_resets=5,
                             flight_dir=str(tmp_path))
        try:
            for _ in range(2):
                fut = eng.submit([5, 6], max_new_tokens=4)
                sched.poison_next = 1
                _drain(fut)
                time.sleep(0.05)
            assert eng.resets_total == 2
            dumps = sorted(p for p in os.listdir(str(tmp_path))
                           if p.startswith("flight_serving_engine"))
            assert len(dumps) >= 2, dumps
        finally:
            eng.stop()


# ------------------------------------------- backpressure / shedding ----

class TestBackpressure:
    def test_preempt_youngest_when_head_starves(self):
        """Admission starvation preempts the YOUNGEST slot: the starved
        head admits, the victim requeues (keeping its prefix) and still
        completes with its full budget."""
        sched = _FakeScheduler(slots=1, step_delay=0.002)
        eng = BatchingEngine(sched, watchdog_s=0.0,
                             preempt_after_s=0.2, max_requeues=3)
        try:
            young = eng.submit([9, 9], max_new_tokens=500, seed=1)
            time.sleep(0.1)   # young owns the only slot
            starved = eng.submit([5, 6], max_new_tokens=6, seed=2)
            out = _drain(starved, timeout=10)
            assert out["finish_reason"] == "length"
            assert out["completion_tokens"] == 6
            out_young = _drain(young, timeout=30)
            assert out_young["completion_tokens"] == 500
            reqs = obs_metrics.REGISTRY.counter(
                "llm_requests_requeued_total",
                labels=("reason",)).value(reason="pressure")
            assert reqs >= 1
        finally:
            eng.stop()

    def test_shed_at_submit_with_retry_after(self):
        """Past shed_queue_depth, submit fails fast with Overloaded and
        a positive Retry-After — never a wedged queue."""
        sched = _FakeScheduler(slots=1)
        sched.step_barrier = threading.Event()   # wedge decode politely
        eng = BatchingEngine(sched, watchdog_s=0.0, shed_queue_depth=2)
        try:
            futs = [eng.submit([5, 6], max_new_tokens=4)]
            deadline = time.time() + 5
            # wait for admission so the first request is IN FLIGHT (not
            # queued) before loading the queue — submitting all three
            # back-to-back races the worker's admit and flakes
            while time.time() < deadline and sched.active_count() < 1:
                time.sleep(0.01)
            futs += [eng.submit([5, 6], max_new_tokens=4)
                     for _ in range(2)]   # 2 queued behind the wedge
            while time.time() < deadline and eng.queue_depth() < 2:
                time.sleep(0.01)
            with pytest.raises(Overloaded) as ei:
                eng.submit([7, 8], max_new_tokens=4)
            assert ei.value.retry_after_s > 0
        finally:
            sched.step_barrier.set()
            for f in futs:
                _drain(f)
            eng.stop()

    def test_shed_maps_to_http_503_with_retry_after(self):
        """The runner maps Overloaded to 503 + Retry-After so overload
        is a protocol signal, not a 500."""
        sched = _FakeScheduler(slots=1)
        sched.step_barrier = threading.Event()
        eng = BatchingEngine(sched, watchdog_s=0.0, shed_queue_depth=1)

        class _P:
            def predict(self, request):
                return _drain(eng.submit([5, 6], max_new_tokens=4),
                              timeout=30)

            def ready(self):
                return True

        runner = FedMLInferenceRunner(_P())
        port = runner.start()
        try:
            first = eng.submit([5, 6], max_new_tokens=4)   # holds the slot
            deadline = time.time() + 5
            while time.time() < deadline and not eng._inflight:
                time.sleep(0.01)
            blocker = eng.submit([5, 6], max_new_tokens=4)  # queued: at bound
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/predict",
                data=json.dumps({}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 503
            assert int(ei.value.headers["Retry-After"]) >= 1
            assert json.load(ei.value)["retry_after_s"] > 0
        finally:
            sched.step_barrier.set()
            _drain(first)
            _drain(blocker)
            runner.stop()
            eng.stop()


# -------------------------------------------------- gateway failover ----

class _Echo:
    def __init__(self, tag="ok"):
        self.tag = tag

    def predict(self, request):
        return {"tag": self.tag}

    def ready(self):
        return True


class _DeadReplica:
    """A replica whose port nothing listens on — the dead-port stub the
    retry-re-pick regression test needs."""

    def __init__(self):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        self.port = s.getsockname()[1]
        s.close()   # nothing listens: connects are refused

    def stop(self):
        pass


class TestGatewayFailover:
    def test_retry_never_repicks_the_failed_port(self):
        """Satellite regression: with dead replicas ahead of the live one
        in rotation, every request still lands — the retry excludes every
        port that already failed instead of round-robining back onto
        it."""
        from fedml_tpu.serving.autoscale import Gateway, ReplicaSet
        rs = ReplicaSet(lambda: _Echo(), min_replicas=1, max_replicas=4)
        dead = [_DeadReplica(), _DeadReplica()]
        with rs._lock:
            rs.replicas = dead + rs.replicas   # dead ports rotate first
        gw = Gateway(rs, window_s=2.0, max_failovers=3, backoff_seed=0)
        try:
            for _ in range(4):   # every rotation offset
                assert gw.predict({"x": 1}, timeout=5)["tag"] == "ok"
        finally:
            with rs._lock:
                rs.replicas = [r for r in rs.replicas
                               if not isinstance(r, _DeadReplica)]
            rs.stop()

    def test_all_ports_dead_raises_the_connect_error(self):
        from fedml_tpu.serving.autoscale import Gateway, ReplicaSet
        rs = ReplicaSet.__new__(ReplicaSet)
        rs._lock = threading.Lock()
        rs._draining = set()
        rs.replicas = [_DeadReplica()]
        gw = Gateway(rs, window_s=2.0, backoff_seed=0)
        with pytest.raises((urllib.error.URLError, OSError)):
            gw.predict({"x": 1}, timeout=5)

    def test_unhealthy_replica_is_routed_around(self):
        """A replica whose /healthz says non-ok (tripped watchdog) is
        quarantined after one failure and traffic flows to its healthy
        sibling."""
        from fedml_tpu.serving.autoscale import Gateway, ReplicaSet

        class _Sick(_Echo):
            def predict(self, request):
                raise RuntimeError("wedged")   # 500s every request

            def health(self):
                return {"status": "stalled"}

        rs = ReplicaSet(lambda: _Echo(), min_replicas=2, max_replicas=2)
        gw = Gateway(rs, window_s=2.0, backoff_seed=0,
                     unhealthy_ttl_s=30.0)
        try:
            sick_port = rs.ports()[0]
            gw.probe_health(sick_port)   # healthy now: no quarantine
            assert not gw._is_quarantined(sick_port)
            # swap a sick predictor onto replica 0's runner
            rs.replicas[0].predictor = _Sick()
            rs.replicas[0].routes["/predict"] = \
                rs.replicas[0].predictor.predict
            assert not gw.probe_health(sick_port)   # healthz 503 now
            assert gw._is_quarantined(sick_port)
            live = rs.ports()[1]
            for _ in range(4):   # all traffic lands on the healthy one
                assert gw.predict({"x": 1}, timeout=5)["tag"] == "ok"
        finally:
            rs.stop()

    def test_draining_replica_leaves_rotation_then_restart(self):
        """The drain -> finish-in-flight -> restart seam: a draining port
        vanishes from ports(), the gateway keeps serving, restart swaps
        in a fresh ready replica with zero failed requests."""
        from fedml_tpu.serving.autoscale import Gateway, ReplicaSet
        rs = ReplicaSet(lambda: _Echo(), min_replicas=2, max_replicas=3)
        gw = Gateway(rs, window_s=2.0, backoff_seed=0)
        try:
            victim = rs.ports()[0]
            rs.drain(victim)
            assert victim not in rs.ports()
            assert victim in rs.ports(include_draining=True)
            for _ in range(4):
                assert gw.predict({"x": 1}, timeout=5)["tag"] == "ok"
            rs.undrain(victim)
            fresh = rs.restart_replica(victim, grace_s=0.05)
            assert fresh != victim
            assert victim not in rs.ports()
            assert fresh in rs.ports()
            for _ in range(4):
                assert gw.predict({"x": 1}, timeout=5)["tag"] == "ok"
        finally:
            rs.stop()

    def test_zero_is_a_legal_fault_index(self):
        """Regression: 0 == False in Python — crash-at-request-0 /
        nan-at-step-0 configured via args must not read as 'unset'."""
        class _A:
            chaos_seed = 1
            chaos_serving_crash_at_request = 0
            chaos_serving_nan_at_step = 0
        plan = FaultPlan.from_args(_A())
        assert plan.serving_crash_due(0)
        assert plan.serving_decode_fault(0) == "nan"
        assert plan.injects_serving_faults

    def test_parked_engine_503_is_routed_around(self):
        """A replica whose engine parked unhealthy (reset budget
        exhausted) answers 503 via the Overloaded mapping — the gateway
        quarantines it and serves from the healthy sibling instead of
        surfacing a 500."""
        from fedml_tpu.serving.autoscale import Gateway, ReplicaSet

        class _Parked(_Echo):
            def predict(self, request):
                raise Overloaded("engine unhealthy (reset budget "
                                 "exhausted)", retry_after_s=30.0)

        rs = ReplicaSet(lambda: _Echo(), min_replicas=2, max_replicas=2)
        gw = Gateway(rs, window_s=2.0, backoff_seed=0,
                     unhealthy_ttl_s=30.0)
        try:
            rs.replicas[0].predictor = _Parked()
            rs.replicas[0].routes["/predict"] = \
                rs.replicas[0].predictor.predict
            for _ in range(4):
                assert gw.predict({"x": 1}, timeout=5)["tag"] == "ok"
            assert gw._is_quarantined(rs.ports()[0])
        finally:
            rs.stop()

    def test_chaos_connection_drops_are_retried_and_ledgered(self):
        """Seeded gateway->replica connection drops fail over instead of
        surfacing, and land in the fault ledger."""
        from fedml_tpu.serving.autoscale import Gateway, ReplicaSet
        plan = FaultPlan(seed=5, serving_conn_drop_prob=0.4)
        ledger = FaultLedger()
        inj = ServingChaosInjector(plan, ledger=ledger)
        rs = ReplicaSet(lambda: _Echo(), min_replicas=2, max_replicas=2)
        gw = Gateway(rs, window_s=2.0, backoff_seed=0, chaos=inj,
                     unhealthy_ttl_s=0.05)
        try:
            for _ in range(12):
                assert gw.predict({"x": 1}, timeout=5)["tag"] == "ok"
            drops = [e for e in ledger.serving_events()
                     if e["kind"] == "conn_drop"]
            assert drops   # the seeded plan fired at least once
        finally:
            rs.stop()


# --------------------------------- recovery determinism (real model) ----

class TestRecoveryDeterminism:
    def test_seeded_sampled_request_replays_bit_identical(self,
                                                          lora_setup):
        """Acceptance pin: a seeded SAMPLED request interrupted mid-decode
        by an injected engine reset replays bit-identical remaining
        tokens after requeue — stateless (seed, position) sampling makes
        recompute-from-prompt exact."""
        _, bundle, params, tok = lora_setup
        reference = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 16, "prefill_chunk": 8})
        try:
            want = reference.generate("replay me exactly",
                                      max_new_tokens=24,
                                      temperature=1.3, seed=42)
        finally:
            reference.close()
        plan = FaultPlan(seed=1, serving_nan_at_step=6)
        inj = ServingChaosInjector(plan)
        disturbed = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 16, "prefill_chunk": 8,
                        "max_resets": 4, "chaos": inj})
        try:
            got = disturbed.generate("replay me exactly",
                                     max_new_tokens=24,
                                     temperature=1.3, seed=42)
            eng = disturbed.engine
            assert eng.resets_total >= 1, \
                "the injected NaN never triggered a reset"
            assert got["text"] == want["text"]
            assert got["completion_tokens"] == want["completion_tokens"]
            assert got["finish_reason"] == want["finish_reason"]
        finally:
            disturbed.close()


# ----------------------------------------------------- chat mapping ----

class TestFinishReasonMapping:
    def test_openai_route_maps_server_cuts_to_length_with_detail(
            self, lora_setup):
        """The OpenAI route keeps the client-compatible enum and carries
        the native reason in finish_reason_detail."""
        _, bundle, params, tok = lora_setup
        pred = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 16, "prefill_chunk": 8,
                        "deadline_s": 0.03})
        try:
            out = pred.chat({"messages": [
                {"role": "user", "content": "a very long story"}],
                "max_tokens": 64})
            choice = out["choices"][0]
            assert choice["finish_reason"] in ("stop", "length")
            assert choice["finish_reason_detail"] in (
                "stop", "length", "deadline", "preempted")
            if choice["finish_reason_detail"] in ("deadline", "preempted"):
                assert choice["finish_reason"] == "length"
        finally:
            pred.close()


# ---------------------------------------------------- chaos soak (c8) ----

@pytest.mark.slow
class TestServingChaosSoak:
    def test_c8_crash_stall_nan_soak_all_complete_compile_once(
            self, lora_setup, xla_compile_counter):
        """The acceptance pin: under a seeded crash+stall+NaN plan, an
        8-concurrent session completes EVERY request with zero
        client-visible failures, the ledger balances injected faults
        against observed resets, and recovery costs zero steady-state
        recompiles."""
        _, bundle, params, tok = lora_setup
        plan = FaultPlan(seed=13, serving_nan_prob=0.02,
                         serving_stall_prob=0.02, serving_stall_s=30.0)
        ledger = FaultLedger()
        inj = ServingChaosInjector(plan, ledger=ledger)
        pred = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 4, "block_size": 16, "prefill_chunk": 8,
                        "watchdog_s": 0.3, "max_resets": 64,
                        "max_requeues": 8, "chaos": inj})
        eng = pred.engine
        try:
            pred.generate("warm", max_new_tokens=2)   # compile warmup
            xla_compile_counter.reset()

            def one(i):
                return pred.generate(
                    f"soak request {i} {'pad ' * (i % 5)}",
                    max_new_tokens=10,
                    temperature=(0.0 if i % 2 else 1.1), seed=i)

            with cf.ThreadPoolExecutor(8) as ex:
                outs = list(ex.map(one, range(24)))
            assert len(outs) == 24
            # zero client-visible failures: every request resolves with
            # a natural finish (the plan's faults were all recovered)
            assert all(o["finish_reason"] in ("stop", "length")
                       for o in outs), [o["finish_reason"] for o in outs]
            # the plan actually fired, and every injected engine fault
            # is balanced by an observed recovery episode
            injected = [e for e in ledger.serving_events()
                        if e["kind"] in ("nan", "stall")]
            assert injected, "seeded plan injected nothing — dead soak"
            assert eng.resets_total >= 1
            assert eng.resets_total <= len(injected)
            assert eng.health()["status"] == "ok"
            # recovery rebuilt pools/slots with the SAME geometry: zero
            # steady-state recompiles
            assert xla_compile_counter.delta() == 0
        finally:
            pred.close()

    def test_gateway_masks_replica_crash_and_conn_drops(self, lora_setup):
        """Zero client-visible failures under replica crash + connection
        drops: an in-process replica severs its connection at request N
        (the process-kill analogue) and the seeded plan drops gateway
        connects — the health-aware failover retries every one onto the
        healthy sibling, so ALL requests complete."""
        from fedml_tpu.serving.autoscale import Gateway, ReplicaSet
        _, bundle, params, tok = lora_setup

        built = []

        def factory():
            pred = CausalLMPredictor(
                bundle, params, tokenizer=tok, mode="batch",
                batch_opts={"slots": 2, "block_size": 16,
                            "prefill_chunk": 8})
            built.append(pred)
            return pred

        crash_inj = ServingChaosInjector(
            FaultPlan(seed=2, serving_crash_at_request=3))

        class _CrashyRunner(ChatCompletionRunner):
            def __init__(self, predictor):
                # the FIRST replica gets the crash plan; siblings are
                # healthy (one injector fires once across the fleet)
                super().__init__(predictor,
                                 chaos=crash_inj if not hasattr(
                                     _CrashyRunner, "_armed") else None)
                _CrashyRunner._armed = True

        drop_inj = ServingChaosInjector(
            FaultPlan(seed=5, serving_conn_drop_prob=0.2),
            ledger=FaultLedger())
        rs = ReplicaSet(predictor_factory=factory, min_replicas=2,
                        max_replicas=2, runner_cls=_CrashyRunner)
        gw = Gateway(rs, window_s=5.0, backoff_seed=0, chaos=drop_inj,
                     unhealthy_ttl_s=0.2, max_failovers=4)
        req = {"messages": [{"role": "user", "content": "ping"}],
               "max_tokens": 6}
        try:
            outs = []
            with cf.ThreadPoolExecutor(4) as ex:
                futs = [ex.submit(gw.predict, dict(req),
                                  timeout=30,
                                  path="/v1/chat/completions")
                        for _ in range(12)]
                outs = [f.result(timeout=60) for f in futs]
            assert len(outs) == 12
            assert all(o["object"] == "chat.completion" for o in outs)
            crashed = crash_inj.ledger.serving_events()
            assert any(e["kind"] == "replica_crash" for e in crashed), \
                "the crash never fired — dead scenario"
        finally:
            rs.stop()
            for p in built:
                p.close()

    def test_subprocess_replica_crash_heals_and_serves(self, tmp_path,
                                                       lora_setup):
        """Replica crash-at-request-N (hard, os._exit in the subprocess):
        the gateway surfaces no garbage, the health check replaces the
        corpse, and the fleet keeps serving."""
        import jax
        from fedml_tpu.serving import save_model
        from fedml_tpu.serving.autoscale import (
            Gateway, ReplicaSet, subprocess_replica_factory)
        args, bundle, params, tok = lora_setup
        params_path = os.path.join(str(tmp_path), "model.fmtpu")
        save_model(params, params_path)
        crash_args = Arguments(**{**{k: v for k, v in
                                     vars(args).items()
                                     if not k.startswith("_")},
                                  "chaos_serving_crash_at_request": 1})
        factory = subprocess_replica_factory(
            crash_args, params_path, output_dim=1,
            workdir=str(tmp_path), kind="causal_lm")
        rs = ReplicaSet(replica_factory=factory, min_replicas=1,
                        max_replicas=2)
        gw = Gateway(rs, window_s=5.0, backoff_seed=0)
        req = {"messages": [{"role": "user", "content": "ping"}],
               "max_tokens": 4}
        try:
            out = gw.predict(req, path="/v1/chat/completions", timeout=60)
            assert out["object"] == "chat.completion"   # request 0 fine
            # request 1 crashes the replica process mid-request; the
            # gateway must fail cleanly (no hang, no garbage)
            try:
                gw.predict(req, path="/v1/chat/completions", timeout=20)
            except Exception:
                pass
            deadline = time.time() + 60
            healed = 0
            while time.time() < deadline and not healed:
                healed = rs.health_check()
                if not healed:
                    time.sleep(0.25)
            assert healed >= 1, "dead replica never replaced"
            out = gw.predict(req, path="/v1/chat/completions", timeout=60)
            assert out["object"] == "chat.completion"
        finally:
            rs.stop()
