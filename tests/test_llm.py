"""LLM pillar tests: model, attention variants, LoRA, sharding, federated
LoRA parity (VERDICT round-1 item 2; reference ``train/llm/`` +
``spotlight_prj/unitedllm/``)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.llm import (
    CausalLM, LLMConfig, init_llm, lora_init, lora_merge, make_lora_apply,
    lora_param_count, CausalLMTrainer, build_llm, run_federated_llm,
)
from fedml_tpu.llm.attention import (
    dense_causal_attention, flash_causal_attention, ring_causal_attention,
    ring_axis,
)

pytestmark = pytest.mark.slow

CFG = LLMConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                num_layers=2, num_heads=4, max_seq_len=32)


@pytest.fixture(scope="module")
def small_lm():
    return init_llm(CFG, jax.random.PRNGKey(0))


def test_forward_shape_and_causality(small_lm):
    model, params = small_lm
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 16, 64)
    assert logits.dtype == jnp.float32
    # causality: changing a future token must not affect earlier logits
    tokens2 = tokens.at[:, 10].set((tokens[:, 10] + 1) % 64)
    logits2 = model.apply({"params": params}, tokens2)
    np.testing.assert_allclose(logits[:, :10], logits2[:, :10], atol=1e-5)
    assert not np.allclose(logits[:, 10:], logits2[:, 10:])


def test_flash_matches_dense():
    rng = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (2, 16, 2, 8))
               for i in range(3))
    dense = dense_causal_attention(q, k, v)
    flash = flash_causal_attention(q, k, v, 8, 8)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(flash),
                               atol=1e-5)
    # backward is the Pallas dQ/dKdV kernel pair — parity for ALL inputs
    def loss(fn):
        return lambda q, k, v: (fn(q, k, v) * jnp.cos(
            jnp.arange(q.shape[-1], dtype=jnp.float32))).sum()
    gd = jax.grad(loss(dense_causal_attention), argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss(lambda q, k, v: flash_causal_attention(q, k, v, 8, 8)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gd, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_key_padding_mask():
    """Flash supports key-padding masks in both directions; masked keys get
    zero probability (fwd parity vs dense) and zero dK/dV rows."""
    rng = jax.random.PRNGKey(1)
    b, s, h, d = 2, 32, 2, 8
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (b, s, h, d))
               for i in range(3))
    mask = (jax.random.uniform(rng, (b, s)) > 0.3).astype(jnp.float32)
    mask = mask.at[:, 0].set(1.0)  # row 0 live so no query sees zero keys
    dense = dense_causal_attention(q, k, v, attn_mask=mask)
    flash = flash_causal_attention(q, k, v, 8, 8, attn_mask=mask)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(flash),
                               atol=1e-5)
    def loss(fn):
        return lambda q, k, v: (fn(q, k, v) ** 2).sum()
    gd = jax.grad(loss(lambda q, k, v: dense_causal_attention(
        q, k, v, attn_mask=mask)), argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss(lambda q, k, v: flash_causal_attention(
        q, k, v, 8, 8, attn_mask=mask)), argnums=(0, 1, 2))(q, k, v)
    for a, bb in zip(gd, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb), atol=1e-4)
    # masked keys contribute nothing: their dK/dV rows are exactly zero
    dk, dv = np.asarray(gf[1]), np.asarray(gf[2])
    dead = np.asarray(mask) == 0
    assert np.all(dk[dead] == 0) and np.all(dv[dead] == 0)


def test_flash_all_masked_row_is_zero():
    """A query row whose every visible key is masked (mid-sequence key
    mask covering its own diagonal) must output exactly zero — not an
    unmasked average of V (ADVICE r3: exp(NEG_INF - NEG_INF) = 1)."""
    rng = jax.random.PRNGKey(2)
    b, s, h, d = 1, 16, 1, 8
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (b, s, h, d))
               for i in range(3))
    mask = jnp.ones((b, s), jnp.float32).at[:, :4].set(0.0)
    out = flash_causal_attention(q, k, v, 8, 8, attn_mask=mask)
    # queries 0..3 see only keys 0..q (all masked) -> exact zeros
    assert np.all(np.asarray(out)[:, :4] == 0.0)
    # live rows still match dense
    dense = dense_causal_attention(q, k, v, attn_mask=mask)
    np.testing.assert_allclose(np.asarray(dense)[:, 4:],
                               np.asarray(out)[:, 4:], atol=1e-5)
    # same contract for ring attention (mask rotates with K/V)
    from fedml_tpu.core.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from fedml_tpu.core.mesh import build_mesh
    mesh = build_mesh({"sp": 4}, devices=jax.devices()[:4])
    ring = shard_map(
        lambda q, k, v, m: ring_causal_attention(q, k, v, "sp", 4,
                                                 attn_mask=m),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3 + (P(None, "sp"),),
        out_specs=P(None, "sp"), check_vma=False)(q, k, v, mask)
    assert np.all(np.asarray(ring)[:, :4] == 0.0)
    np.testing.assert_allclose(np.asarray(dense)[:, 4:],
                               np.asarray(ring)[:, 4:], atol=1e-5)


def test_nonaligned_seq_len_pads_to_lane_multiple():
    """s=100 (not a multiple of 128) must be handled by pad+slice, matching
    dense exactly on the real rows (ADVICE r3: 125-row blocks are not
    lane-aligned on hardware)."""
    rng = jax.random.PRNGKey(3)
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (2, 100, 2, 8))
               for i in range(3))
    dense = dense_causal_attention(q, k, v)
    flash = flash_causal_attention(q, k, v)
    assert flash.shape == dense.shape
    np.testing.assert_allclose(np.asarray(dense), np.asarray(flash),
                               atol=1e-5)


def test_flash_bwd_never_materializes_scores():
    """Training-memory property: at s=4096 the compiled fwd+bwd must not
    allocate an [s, s] f32 buffer (64 MiB); flash peak temp stays under a
    quarter of that. TPU-only — interpret mode has no memory contract."""
    import pytest
    if jax.default_backend() != "tpu":
        pytest.skip("memory contract is a compiled-TPU property")
    s, d = 4096, 64
    q = jnp.zeros((1, s, 1, d), jnp.bfloat16)

    def train_loss(q, k, v):
        return flash_causal_attention(q, k, v).astype(jnp.float32).sum()

    compiled = jax.jit(jax.grad(train_loss, argnums=(0, 1, 2))).lower(
        q, q, q).compile()
    mem = compiled.memory_analysis()
    scores_bytes = s * s * 4
    assert mem.temp_size_in_bytes < scores_bytes // 4, (
        f"temp {mem.temp_size_in_bytes} vs scores {scores_bytes}")


def test_ring_matches_dense_multidevice():
    from fedml_tpu.core.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from fedml_tpu.core.mesh import build_mesh

    mesh = build_mesh({"sp": 4}, devices=jax.devices()[:4])
    rng = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (2, 32, 2, 8))
               for i in range(3))
    dense = dense_causal_attention(q, k, v)

    ring = shard_map(
        lambda q, k, v: ring_causal_attention(q, k, v, "sp", 4),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False)(q, k, v)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                               atol=1e-5)


def test_ring_gradients_match_dense():
    """Ring attention must be TRAINABLE: gradients through the ppermute
    accumulation (sequence-parallel backward) match the dense single-
    device gradients — the property a long-context fine-tune relies on."""
    from fedml_tpu.core.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from fedml_tpu.core.mesh import build_mesh

    mesh = build_mesh({"sp": 4}, devices=jax.devices()[:4])
    rng = jax.random.PRNGKey(5)
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (2, 32, 2, 8))
               for i in range(3))
    mask = (jax.random.uniform(rng, (2, 32)) > 0.25).astype(jnp.float32)
    mask = mask.at[:, 0].set(1.0)

    def loss_dense(q, k, v):
        out = dense_causal_attention(q, k, v, attn_mask=mask)
        return (out.astype(jnp.float32) ** 2).sum()

    ring_fn = shard_map(
        lambda q, k, v, m: ring_causal_attention(q, k, v, "sp", 4,
                                                 attn_mask=m),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3 + (P(None, "sp"),),
        out_specs=P(None, "sp"), check_vma=False)

    def loss_ring(q, k, v):
        return (ring_fn(q, k, v, mask).astype(jnp.float32) ** 2).sum()

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gd, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4)


def test_ring_bwd_residuals_stay_linear_in_s():
    """Training-memory contract for ring attention (VERDICT r4 item 3),
    mirroring test_flash_bwd_never_materializes_scores: the fold is
    rematerialized, so the backward must NOT stack the per-step
    [s_loc, s_loc] probability block across the axis_size ring steps —
    compiled temp memory stays well under the full [s, s] score matrix
    (the un-remat'd form measures ~3x over this bound at s=4096 and the
    gap grows with s)."""
    from fedml_tpu.core.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from fedml_tpu.core.mesh import build_mesh

    s, d, sp = 4096, 64, 4
    mesh = build_mesh({"sp": sp}, devices=jax.devices()[:sp])
    q = jnp.zeros((1, s, 1, d), jnp.float32)

    def loss(q, k, v):
        out = shard_map(
            lambda a, b, c: ring_causal_attention(a, b, c, "sp", sp),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False)(q, k, v)
        return out.sum()

    compiled = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(
        q, q, q).compile()
    mem = compiled.memory_analysis()
    if mem is None:
        pytest.skip("backend reports no memory analysis")
    scores_bytes = s * s * 4
    assert mem.temp_size_in_bytes < scores_bytes // 2, (
        f"ring bwd temp {mem.temp_size_in_bytes} vs full scores "
        f"{scores_bytes} — remat contract broken")
    # more shards -> smaller per-device block -> less temp memory: the
    # property that lets context scale with chip count
    mesh8 = build_mesh({"sp": 8}, devices=jax.devices()[:8])

    def loss8(q, k, v):
        out = shard_map(
            lambda a, b, c: ring_causal_attention(a, b, c, "sp", 8),
            mesh=mesh8, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False)(q, k, v)
        return out.sum()

    mem8 = jax.jit(jax.grad(loss8, argnums=(0, 1, 2))).lower(
        q, q, q).compile().memory_analysis()
    assert mem8.temp_size_in_bytes < mem.temp_size_in_bytes


def test_ring_forward_full_model():
    """Sequence-parallel forward of the whole decoder matches the dense
    single-device forward (global RoPE positions + causal mask)."""
    from fedml_tpu.core.mesh import build_mesh
    from fedml_tpu.llm.sharding import make_ring_forward

    cfg_ring = LLMConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                         num_layers=2, num_heads=4, max_seq_len=32,
                         attention_impl="ring")
    model_ring = CausalLM(cfg_ring)
    model_dense, params = init_llm(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    want = model_dense.apply({"params": params}, tokens)

    mesh = build_mesh({"sp": 4}, devices=jax.devices()[:4])
    fwd = make_ring_forward(
        lambda p, t, m: model_ring.apply({"params": p}, t, attn_mask=m),
        mesh)
    got = fwd(params, tokens)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got), atol=2e-4)

    # key-padding: last 8 tokens of row 0 are pad. Ring must agree with the
    # dense forward on the real positions (padded-row logits are garbage in
    # both and excluded).
    mask = np.ones((2, 32), np.int32)
    mask[0, 24:] = 0
    want_m = model_dense.apply({"params": params}, tokens,
                               attn_mask=jnp.asarray(mask))
    got_m = fwd(params, tokens, jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(want_m)[mask.astype(bool)],
                               np.asarray(got_m)[mask.astype(bool)],
                               atol=2e-4)


def test_lora_zero_init_and_delta(small_lm):
    model, params = small_lm
    lora = lora_init(jax.random.PRNGKey(2), params, rank=4)
    assert lora_param_count(lora) > 0
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
    base_out = model.apply({"params": params}, tokens)
    merged = lora_merge(params, lora)
    merged_out = model.apply({"params": merged}, tokens)
    np.testing.assert_allclose(np.asarray(base_out), np.asarray(merged_out),
                               atol=1e-6)  # b=0 → zero effect
    # non-zero b changes the output
    bumped = jax.tree_util.tree_map(lambda a: a + 0.1, lora)
    out2 = model.apply({"params": lora_merge(params, bumped)}, tokens)
    assert not np.allclose(np.asarray(base_out), np.asarray(out2))


def test_lora_training_reduces_loss(small_lm):
    model, params = small_lm
    apply_fn = make_lora_apply(
        lambda p, x, rng=None, train=False: model.apply({"params": p}, x),
        params)
    spec = CausalLMTrainer(apply_fn)
    lora = lora_init(jax.random.PRNGKey(2), params, rank=4)
    x = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 4, 64)
    batch = {"x": x, "y": x, "mask": jnp.ones(4)}

    import optax
    opt = optax.adam(1e-2)
    state = opt.init(lora)
    loss0 = None

    @jax.jit
    def step(lora, state):
        (loss, _), g = jax.value_and_grad(spec.loss, has_aux=True)(
            lora, batch, jax.random.PRNGKey(0))
        up, state = opt.update(g, state, lora)
        return optax.apply_updates(lora, up), state, loss

    for i in range(20):
        lora, state, loss = step(lora, state)
        if loss0 is None:
            loss0 = float(loss)
    assert float(loss) < loss0 * 0.9


def test_fsdp_tp_sharded_step():
    """Train step jitted over a fsdp×tensor mesh compiles, executes, and
    matches the unsharded step numerically."""
    from fedml_tpu.core.mesh import build_mesh
    from fedml_tpu.llm.sharding import (
        llm_param_specs, make_sharded_train_step, shard_llm_params)
    import optax

    cfg = LLMConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                    num_layers=2, num_heads=4, max_seq_len=16,
                    tie_embeddings=False)
    model, params = init_llm(cfg, jax.random.PRNGKey(0))
    spec = CausalLMTrainer(
        lambda p, x, rng=None, train=False: model.apply({"params": p}, x))
    x = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 4, 64)
    batch = {"x": x, "y": x, "mask": jnp.ones(8)}
    opt = optax.sgd(0.1)

    # golden: unsharded
    (l0, _), g = jax.value_and_grad(spec.loss, has_aux=True)(
        params, batch, jax.random.PRNGKey(0))
    up, _ = opt.update(g, opt.init(params), params)
    want = jax.tree_util.tree_map(lambda p, u: p + u, params, up)

    mesh = build_mesh({"data": 2, "fsdp": 2, "tensor": 2},
                      devices=jax.devices()[:8])
    specs = llm_param_specs(params, mesh)
    with mesh:
        sharded = shard_llm_params(params, mesh)
        step = make_sharded_train_step(
            lambda p, b, r: spec.loss(p, b, r), opt, mesh, specs)
        new_params, _, loss = step(sharded, opt.init(sharded), batch,
                                   jax.random.PRNGKey(0))
    np.testing.assert_allclose(float(loss), float(l0), atol=1e-5)
    for wleaf, gleaf in zip(jax.tree_util.tree_leaves(want),
                            jax.tree_util.tree_leaves(new_params)):
        np.testing.assert_allclose(np.asarray(wleaf), np.asarray(gleaf),
                                   atol=1e-4)


def test_federated_lora_two_silos_parity():
    """2 silos with FedAvg over adapters: with full participation and equal
    shards, the federated run must track single-silo training on the union
    of the data (UnitedLLM round semantics)."""
    common = dict(
        dataset="llm_synth", model="causal_lm", comm_round=3, epochs=1,
        batch_size=8, learning_rate=5e-3, client_optimizer="adam",
        llm_corpus_size=64, llm_max_seq_len=48, llm_hidden_size=32,
        llm_num_layers=1, llm_num_heads=2, llm_intermediate_size=64,
        lora_rank=4, random_seed=7, frequency_of_the_test=10,
        training_type="simulation", backend="sp",
    )
    r2 = run_federated_llm(Arguments(
        client_num_in_total=2, client_num_per_round=2, **common))
    r1 = run_federated_llm(Arguments(
        client_num_in_total=1, client_num_per_round=1, **common))
    # both learn (loss drops below initial-ish level) and agree closely
    assert r2["final_test_loss"] < 6.0
    assert abs(r2["final_test_loss"] - r1["final_test_loss"]) < 0.35


def test_hf_llama_import_roundtrip():
    """Fabricated Llama-named torch state dict → flax params → forward."""
    import torch
    from fedml_tpu.llm.hf import convert_llama_state_dict

    cfg = LLMConfig(vocab_size=32, hidden_size=16, intermediate_size=32,
                    num_layers=1, num_heads=2, max_seq_len=8)
    h, i, v = 16, 32, 32
    sd = {
        "model.embed_tokens.weight": torch.randn(v, h),
        "model.norm.weight": torch.ones(h),
        "model.layers.0.input_layernorm.weight": torch.ones(h),
        "model.layers.0.post_attention_layernorm.weight": torch.ones(h),
        "model.layers.0.self_attn.q_proj.weight": torch.randn(h, h),
        "model.layers.0.self_attn.k_proj.weight": torch.randn(h, h),
        "model.layers.0.self_attn.v_proj.weight": torch.randn(h, h),
        "model.layers.0.self_attn.o_proj.weight": torch.randn(h, h),
        "model.layers.0.mlp.gate_proj.weight": torch.randn(i, h),
        "model.layers.0.mlp.up_proj.weight": torch.randn(i, h),
        "model.layers.0.mlp.down_proj.weight": torch.randn(h, i),
    }
    params = convert_llama_state_dict(sd, cfg)
    model = CausalLM(cfg)
    ref_init = model.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, 4), jnp.int32))["params"]
    # identical treedef + shapes as a fresh init
    got = {tuple(p): l.shape for p, l in
           jax.tree_util.tree_flatten_with_path(params)[0]}
    want = {tuple(p): l.shape for p, l in
            jax.tree_util.tree_flatten_with_path(ref_init)[0]}
    assert {str(k): v for k, v in got.items()} == \
        {str(k): v for k, v in want.items()}
    logits = model.apply({"params": params},
                         jnp.zeros((1, 4), jnp.int32))
    assert logits.shape == (1, 4, 32)
    assert np.isfinite(np.asarray(logits)).all()
