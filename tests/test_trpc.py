"""TRPC backend (torch.distributed.rpc TensorPipe): echo across two
spawned single-rank processes (reference trpc_comm_manager.py:21)."""

import multiprocessing as mp
import os
import sys

import pytest

pytestmark = pytest.mark.slow


def _rank_main(rank, port, q):
    # fresh process: plain CPU jax/torch, independent RPC world
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    os.environ["MASTER_ADDR"] = "127.0.0.1"
    os.environ["MASTER_PORT"] = str(port)
    import threading

    from fedml_tpu.core.distributed.communication.base_com_manager import (
        Observer)
    from fedml_tpu.core.distributed.communication.message import Message
    from fedml_tpu.core.distributed.communication.trpc import TRPCCommManager

    mgr = TRPCCommManager(rank, world_size=2)

    class Sink(Observer):
        def __init__(self):
            self.got = threading.Event()
            self.payload = None

        def receive_message(self, msg_type, msg):
            self.payload = msg.get("payload")
            self.got.set()

    sink = Sink()
    mgr.add_observer(sink)
    t = threading.Thread(target=mgr.handle_receive_message, daemon=True)
    t.start()
    if rank == 0:
        msg = Message("trpc_echo", 0, 1)
        msg.add_params("payload", [4, 5, 6])
        mgr.send_message(msg)
        ok = sink.got.wait(timeout=30)   # rank 1 echoes back
        q.put(("r0", ok, sink.payload))
    else:
        ok = sink.got.wait(timeout=30)
        if ok:
            reply = Message("trpc_echo", 1, 0)
            reply.add_params("payload", sink.payload)
            mgr.send_message(reply)
        q.put(("r1", ok, sink.payload))
    import time
    time.sleep(1.0)
    mgr.stop_receive_message()


def test_trpc_two_process_echo():
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = 29611
    procs = [ctx.Process(target=_rank_main, args=(r, port, q))
             for r in (0, 1)]
    for p in procs:
        p.start()
    results = {}
    for _ in range(2):
        name, ok, payload = q.get(timeout=120)
        results[name] = (ok, payload)
    for p in procs:
        p.join(timeout=30)
        if p.is_alive():
            p.terminate()
    assert results["r1"] == (True, [4, 5, 6])
    assert results["r0"] == (True, [4, 5, 6])
