"""Scheduler: LPT balance, exact 2-worker DP, runtime regression."""

import numpy as np

from fedml_tpu.core.schedule import (RuntimeEstimator, SeqTrainScheduler,
                                     balanced_schedule)


def test_lpt_beats_round_robin():
    costs = [10, 1, 1, 1, 10, 1, 1, 1]
    sched, makespan = SeqTrainScheduler(costs, 2).schedule()
    assert makespan == 13  # optimal: {10,1,1,1} per worker
    rr = max(sum(costs[0::2]), sum(costs[1::2]))  # round-robin: 22 vs 4
    assert makespan < rr


def test_dp_two_workers_exact():
    costs = [3, 1, 4, 2, 2]
    sched, makespan = SeqTrainScheduler(costs, 2, mode="dp").schedule()
    assert makespan == 6  # perfect split of 12
    got = {frozenset(sched[0]), frozenset(sched[1])}
    all_items = sched[0] + sched[1]
    assert sorted(all_items) == [0, 1, 2, 3, 4]


def test_all_clients_assigned():
    sched, _ = SeqTrainScheduler([5, 4, 3, 2, 1], 3).schedule()
    assert sorted(i for dev in sched for i in dev) == [0, 1, 2, 3, 4]


def test_runtime_estimator_fits_linear():
    est = RuntimeEstimator()
    for n in [10, 20, 40, 80]:
        est.record(0, n, 0.5 * n + 2.0)
    assert abs(est.predict(0, 100) - 52.0) < 1e-6


def test_balanced_schedule_maps_ids():
    sampled = [7, 3, 9]
    costs = {3: 1.0, 7: 5.0, 9: 1.0}
    costs_arr = [costs.get(i, 0.0) for i in range(10)]
    out = balanced_schedule(sampled, costs_arr, 2)
    flat = sorted(i for dev in out for i in dev)
    assert flat == [3, 7, 9]
    loads = [sum(costs[i] for i in dev) for dev in out]
    assert max(loads) == 5.0  # the heavy client is alone


# --- partial-availability schedules from a FaultPlan (chaos subsystem) ------

def _survivors(plan, round_idx, sampled):
    faults = plan.round_faults(round_idx, sampled)
    return [c for c in sampled if c not in faults.dropped], faults


def test_schedule_over_faultplan_survivors():
    """Dropped clients leave the schedule entirely; every survivor is
    still assigned exactly once and the makespan only shrinks."""
    from fedml_tpu.core.chaos import FaultPlan

    plan = FaultPlan(seed=21, dropout_prob=0.3)
    sampled = list(range(12))
    costs = [float(1 + (i % 4)) for i in range(12)]
    survivors, faults = _survivors(plan, 0, sampled)
    assert 0 < len(faults.dropped) < len(sampled)
    out = balanced_schedule(survivors, costs, 4)
    flat = sorted(i for dev in out for i in dev)
    assert flat == sorted(survivors)
    assert not any(c in flat for c in faults.dropped)
    _, full_makespan = SeqTrainScheduler(
        [costs[c] for c in sampled], 4).schedule()
    _, part_makespan = SeqTrainScheduler(
        [costs[c] for c in survivors], 4).schedule()
    assert part_makespan <= full_makespan


def test_schedule_under_faultplan_is_deterministic():
    """Same chaos seed -> same survivors -> same schedule, across plan
    instances (the property crash-resume scheduling leans on)."""
    from fedml_tpu.core.chaos import FaultPlan

    costs = [float(1 + (i % 3)) for i in range(10)]
    outs = []
    for _ in range(2):
        plan = FaultPlan(seed=5, dropout_prob=0.25)
        per_round = []
        for r in range(6):
            survivors, _ = _survivors(plan, r, list(range(10)))
            per_round.append(balanced_schedule(survivors, costs, 3))
        outs.append(per_round)
    assert outs[0] == outs[1]


def test_straggler_costs_reweight_schedule():
    """A straggler running work_scale of its steps costs work_scale of its
    load — LPT must rebalance with the scaled costs."""
    from fedml_tpu.core.chaos import FaultPlan

    plan = FaultPlan(seed=2, straggler_prob=0.5, straggler_work=0.5)
    sampled = list(range(8))
    faults = plan.round_faults(1, sampled)
    assert faults.work_scale  # some straggler fired
    base = [4.0] * 8
    scaled = [base[c] * faults.scale_for(c) for c in sampled]
    sched, makespan = SeqTrainScheduler(scaled, 2).schedule()
    assert sorted(i for dev in sched for i in dev) == sampled
    assert makespan < sum(base) / 2  # stragglers shrank the load


def test_dp_mode_on_survivors():
    """The exact 2-worker DP path also takes FaultPlan-filtered loads."""
    from fedml_tpu.core.chaos import FaultPlan

    plan = FaultPlan(seed=3, dropout_prob=0.4)
    sampled = list(range(6))
    survivors, faults = _survivors(plan, 0, sampled)
    assert faults.dropped  # seed chosen so someone drops
    costs = [float(i + 1) for i in range(len(survivors))]
    sched, makespan = SeqTrainScheduler(costs, 2, mode="dp").schedule()
    assert sorted(i for dev in sched for i in dev) == list(
        range(len(survivors)))
    assert makespan >= sum(costs) / 2


def test_runtime_estimator_with_partial_rounds():
    """Observed round times from straggler rounds still fit the linear
    model — the estimator sees (scaled samples, scaled seconds) pairs."""
    est = RuntimeEstimator()
    for n, scale in [(10, 1.0), (20, 1.0), (40, 0.5), (80, 0.5)]:
        est.record(0, n * scale, (0.5 * n + 2.0) * scale)
    pred = est.predict(0, 50)
    assert 20.0 < pred < 35.0  # still ~linear despite mixed scales
