"""Scheduler: LPT balance, exact 2-worker DP, runtime regression."""

import numpy as np

from fedml_tpu.core.schedule import (RuntimeEstimator, SeqTrainScheduler,
                                     balanced_schedule)


def test_lpt_beats_round_robin():
    costs = [10, 1, 1, 1, 10, 1, 1, 1]
    sched, makespan = SeqTrainScheduler(costs, 2).schedule()
    assert makespan == 13  # optimal: {10,1,1,1} per worker
    rr = max(sum(costs[0::2]), sum(costs[1::2]))  # round-robin: 22 vs 4
    assert makespan < rr


def test_dp_two_workers_exact():
    costs = [3, 1, 4, 2, 2]
    sched, makespan = SeqTrainScheduler(costs, 2, mode="dp").schedule()
    assert makespan == 6  # perfect split of 12
    got = {frozenset(sched[0]), frozenset(sched[1])}
    all_items = sched[0] + sched[1]
    assert sorted(all_items) == [0, 1, 2, 3, 4]


def test_all_clients_assigned():
    sched, _ = SeqTrainScheduler([5, 4, 3, 2, 1], 3).schedule()
    assert sorted(i for dev in sched for i in dev) == [0, 1, 2, 3, 4]


def test_runtime_estimator_fits_linear():
    est = RuntimeEstimator()
    for n in [10, 20, 40, 80]:
        est.record(0, n, 0.5 * n + 2.0)
    assert abs(est.predict(0, 100) - 52.0) < 1e-6


def test_balanced_schedule_maps_ids():
    sampled = [7, 3, 9]
    costs = {3: 1.0, 7: 5.0, 9: 1.0}
    costs_arr = [costs.get(i, 0.0) for i in range(10)]
    out = balanced_schedule(sampled, costs_arr, 2)
    flat = sorted(i for dev in out for i in dev)
    assert flat == [3, 7, 9]
    loads = [sum(costs[i] for i in dev) for dev in out]
    assert max(loads) == 5.0  # the heavy client is alone
