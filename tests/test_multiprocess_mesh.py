"""Two-OS-process mesh execution (VERDICT r3 item 3): two separate
interpreters form ONE JAX runtime via ``jax.distributed.initialize``
(through the repo's torchrun-env bootstrap, ``init_silo_process_group``),
run a hierarchical-silo federated round over the global 8-device mesh, and
the result matches the single-process 8-device run — converting "on real
hardware each silo is its own host" from a claim into a tested property.

Reference counterpart: multi-node-without-a-cluster smoke tests
(``tests/smoke_test/simulation_mpi/mpi_host_file``, torchrun
``--nproc_per_node=5``)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multiproc_silo_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _golden():
    """Same round on THIS process's own 8-device CPU mesh."""
    import jax
    from fedml_tpu import data as data_mod, model as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import make_trainer_spec
    from fedml_tpu.cross_silo.hierarchical.trainer import (
        HierarchicalSiloTrainer)
    from fedml_tpu.optimizers.registry import create_optimizer

    args = Arguments(dataset="digits", model="lr", client_num_in_total=2,
                     client_num_per_round=2, comm_round=1, epochs=1,
                     batch_size=32, learning_rate=0.1, random_seed=7,
                     training_type="cross_silo")
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    spec = make_trainer_spec(fed, bundle)
    opt = create_optimizer(args, spec)
    trainer = HierarchicalSiloTrainer(args, fed, bundle, spec, opt,
                                      devices=jax.devices()[:8])
    params = trainer.params_template
    deltas, ws = [], []
    for cid in range(2):
        new_p, n, _ = trainer.train(params, cid, 0)
        deltas.append(jax.tree_util.tree_map(
            lambda a, b: np.asarray(a) - np.asarray(b), new_p, params))
        ws.append(n)
    wsum = sum(ws)
    agg = jax.tree_util.tree_map(
        lambda *ds: sum(w * d for w, d in zip(ws, ds)) / wsum, *deltas)
    out = jax.tree_util.tree_map(
        lambda p, u: np.asarray(p) + u, params, agg)
    flat = np.concatenate([np.asarray(l).ravel() for l in
                           jax.tree_util.tree_leaves(out)])
    return ws, flat


def test_two_process_llm_fsdp_step_matches_single_process(tmp_path):
    """The FedLLM sharded train step (fsdp=4 x tensor=2 mesh) executes
    across TWO OS processes — the multi-host pod program — and matches the
    single-process 8-device result exactly."""
    LLM_WORKER = os.path.join(REPO, "tests", "multiproc_llm_worker.py")
    port = _free_port()
    out_path = str(tmp_path / "llm.json")
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "MASTER_ADDR": "127.0.0.1", "MASTER_PORT": str(port),
            "WORLD_SIZE": "2", "RANK": str(rank),
            "PYTHONPATH": REPO + os.pathsep + os.environ.get(
                "PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, LLM_WORKER, out_path], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process LLM step timed out")
        outs.append(out.decode(errors="replace"))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    with open(out_path) as f:
        got = json.load(f)
    assert got["n_processes"] == 2

    # single-process golden on this process's own 8 CPU devices
    from tests.multiproc_llm_worker import _llm_fsdp_step
    loss, checksum = _llm_fsdp_step()
    assert abs(got["loss"] - loss) < 1e-5
    assert abs(got["checksum"] - checksum) / max(checksum, 1.0) < 1e-5


def test_two_process_mesh_round_matches_single_process(tmp_path):
    port = _free_port()
    out_path = str(tmp_path / "result.json")
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # worker sets its own 4-device flag
        env.update({
            "MASTER_ADDR": "127.0.0.1", "MASTER_PORT": str(port),
            "WORLD_SIZE": "2", "RANK": str(rank),
            "PYTHONPATH": REPO + os.pathsep + os.environ.get(
                "PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, WORKER, out_path], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process mesh round timed out")
        outs.append(out.decode(errors="replace"))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    with open(out_path) as f:
        got = json.load(f)
    assert got["n_processes"] == 2
    assert got["n_global_devices"] == 8

    ws, flat = _golden()
    assert got["weights"] == ws
    np.testing.assert_allclose(np.asarray(got["params"]),
                               flat[:4096], rtol=1e-5, atol=1e-6)
    assert abs(got["params_sum"] - float(flat.sum())) < 1e-3
