"""Million-client control plane (core/selection at population scale).

Covers (1) dense-vs-sparse stats-store parity — the same observation
sequence must yield BIT-IDENTICAL posteriors and selections on both
backends; (2) sparse-store persistence — compacted round-trip, restore
from a legacy dense snapshot, crash-resume through RoundCheckpointer
(orbax restoring saved shapes past a smaller template is load-bearing
and pinned here), LRU eviction at capacity; (3) candidate-pool
selection — partial top-k equivalence, pool knobs, O(m)-shaped draws;
(4) the streaming sampler fast path (small-N draws unchanged, huge-N
draws valid + deterministic); (5) streaming cohort assembly —
brute-force equivalence, eligibility predicates, chunking independence;
(6) the deadline pacer — deterministic given (knobs, history), bounded;
(7) the SP simulator's selection seam (the PR 3/5 gap): strategies +
crash-resume replay. The 1M-client smoke rides the slow gate.
"""

import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core.selection import (ClientStatsStore, DeadlinePacer,
                                      SelectionManager,
                                      SparseClientStatsStore,
                                      StreamingCohortAssembler,
                                      create_strategy, make_stats_store,
                                      partial_top_k, pool_size,
                                      population_chunks)
from fedml_tpu.simulation.sampling import (FAST_SAMPLE_MIN_N,
                                           client_sampling,
                                           sample_ids_streaming)

pytestmark = [pytest.mark.selection, pytest.mark.population]


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=64, client_num_per_round=8,
                comm_round=3, epochs=1, batch_size=16, learning_rate=0.1,
                frequency_of_the_test=2, random_seed=42)
    base.update(kw)
    return Arguments(**base)


def feed_observations(store, n=64, seed=0, rounds=12, k=8):
    """One deterministic observation history, replayable into any
    backend: selections, losses, availability, latencies, verdicts."""
    rng = np.random.default_rng(seed)
    for r in range(rounds):
        ids = rng.choice(n, k, replace=False)
        store.record_selected(r, [int(c) for c in ids])
        for c in ids:
            c = int(c)
            store.record_loss(c, float(rng.gamma(2.0, 1.0)))
            store.record_availability(c, participated=bool(rng.random()
                                                           > 0.25),
                                      work=float(rng.uniform(0.4, 1.0)))
            if rng.random() > 0.5:
                store.record_latency(c, float(rng.gamma(2.0, 3.0)))
            if rng.random() > 0.6:
                store.record_arrival(c, float(rng.gamma(2.0, 2.0)))
        store.record_verdict([int(c) for c in ids],
                             rng.uniform(0.0, 1.0, size=k))
    return store


# --- dense vs sparse parity --------------------------------------------------

class TestDenseSparseParity:
    def _pair(self, n=64):
        dense = feed_observations(ClientStatsStore(n), n=n)
        sparse = feed_observations(SparseClientStatsStore(n), n=n)
        return dense, sparse

    def test_posterior_queries_bit_identical(self):
        dense, sparse = self._pair()
        ids = np.arange(64)
        for q in ("last_loss_for", "rms_loss_for", "reputation_for",
                  "ema_work_for", "latency_for", "times_selected_for",
                  "last_selected_for", "arrival_rate_for"):
            a = getattr(dense, q)(ids)
            b = getattr(sparse, q)(ids)
            np.testing.assert_array_equal(a, b, err_msg=q)

    def test_pooled_reductions_bit_identical(self):
        dense, sparse = self._pair()
        assert dense.population_dropout_mean() \
            == sparse.population_dropout_mean()
        assert dense.observed_rms_mean() == sparse.observed_rms_mean()
        assert dense.observed_latency_median() \
            == sparse.observed_latency_median()
        assert dense._reputation_pop_mean() \
            == sparse._reputation_pop_mean()
        assert dense.num_touched() == sparse.num_touched()

    def test_untouched_ids_answer_dense_defaults(self):
        sparse = SparseClientStatsStore(100)
        sparse.record_loss(3, 1.0)
        ids = [0, 50, 99]
        assert np.all(np.isinf(sparse.last_loss_for(ids)))
        assert np.all(np.isnan(sparse.rms_loss_for(ids)))
        np.testing.assert_array_equal(sparse.reputation_for(ids),
                                      np.ones(3))
        np.testing.assert_array_equal(sparse.ema_work_for(ids), np.ones(3))
        np.testing.assert_array_equal(sparse.last_selected_for(ids),
                                      np.full(3, -1))
        prior = ClientStatsStore(4).dropout_posterior_mean()[0]
        np.testing.assert_allclose(sparse.dropout_posterior_mean(ids),
                                   np.full(3, prior))

    @pytest.mark.parametrize("strategy", ["power_of_choice", "oort",
                                          "reputation"])
    @pytest.mark.parametrize("pool", [0, 24])
    def test_selections_bit_identical(self, strategy, pool):
        """Same observations, same knobs => the SAME cohorts off either
        backend — the backend is an implementation detail, pool on or
        off."""
        dense, sparse = self._pair()
        args = make_args(client_selection=strategy,
                         selection_candidate_pool=pool)
        sd = create_strategy(args, 64, dense)
        ss = create_strategy(args, 64, sparse)
        for r in range(1, 6):
            assert sd.select(r, 8) == ss.select(r, 8), (strategy, pool, r)

    def test_to_dense_roundtrip(self):
        dense, sparse = self._pair()
        twin = sparse.to_dense()
        for f in ClientStatsStore._FIELDS:
            np.testing.assert_array_equal(getattr(dense, f),
                                          getattr(twin, f), err_msg=f)


# --- sparse persistence ------------------------------------------------------

class TestSparsePersistence:
    def test_compacted_roundtrip(self):
        sparse = feed_observations(SparseClientStatsStore(128), n=128)
        st = sparse.state_dict()
        # compacted: rows scale with touched clients, not population
        assert st["ids"].shape[0] == sparse.num_touched() < 128
        back = SparseClientStatsStore(128)
        back.load_state_dict(st)
        ids = np.arange(128)
        np.testing.assert_array_equal(sparse.rms_loss_for(ids),
                                      back.rms_loss_for(ids))
        np.testing.assert_array_equal(sparse.reputation_for(ids),
                                      back.reputation_for(ids))
        assert sparse.population_dropout_mean() \
            == back.population_dropout_mean()

    def test_restores_from_dense_snapshot(self):
        """The backend-switch story: a checkpoint written by the DENSE
        store loads into the sparse store, touched rows only."""
        dense = feed_observations(ClientStatsStore(64), n=64)
        sparse = SparseClientStatsStore(64)
        sparse.load_state_dict(dense.state_dict())
        assert sparse.num_touched() == dense.num_touched()
        ids = np.arange(64)
        for q in ("last_loss_for", "rms_loss_for", "reputation_for",
                  "times_selected_for"):
            np.testing.assert_array_equal(getattr(dense, q)(ids),
                                          getattr(sparse, q)(ids),
                                          err_msg=q)
        assert dense.population_dropout_mean() \
            == sparse.population_dropout_mean()

    def test_rejects_out_of_population_and_over_capacity(self):
        sparse = feed_observations(SparseClientStatsStore(64), n=64)
        st = sparse.state_dict()
        with pytest.raises(ValueError, match="outside this population"):
            SparseClientStatsStore(8).load_state_dict(st)
        with pytest.raises(ValueError, match="capacity"):
            SparseClientStatsStore(64, capacity=4).load_state_dict(st)

    def test_crash_resume_through_round_checkpointer(self, tmp_path):
        """The growing sparse columns ride orbax: a FRESH manager's
        template has fewer rows than the checkpoint, and the restore
        must come back with the SAVED rows (this is the orbax behavior
        the sparse backend depends on — pinned here)."""
        from fedml_tpu.core.checkpoint import RoundCheckpointer
        args = make_args(client_selection="oort", selection_store="sparse",
                         client_num_in_total=256)
        mgr = SelectionManager(args, 256)
        assert isinstance(mgr.store, SparseClientStatsStore)
        feed_observations(mgr.store, n=256, rounds=6)
        ck = RoundCheckpointer(str(tmp_path / "ck"), every_rounds=1)
        ck.maybe_save(0, {"selection": mgr.state_dict()})
        ck.flush()
        fresh = SelectionManager(args, 256)  # template: zero rows
        restored = ck.latest({"selection": fresh.state_dict()})
        assert restored is not None
        fresh.load_state_dict(restored[1]["selection"])
        assert fresh.store.num_touched() == mgr.store.num_touched()
        # identical restored history => identical future cohorts
        for r in range(6, 10):
            assert fresh.select(r, 8) == mgr.select(r, 8)
        ck.close()

    def test_lru_eviction_at_capacity(self):
        sparse = SparseClientStatsStore(1000, capacity=4)
        for c in (1, 2, 3, 4):
            sparse.record_loss(c, float(c))
        sparse.record_loss(1, 9.0)  # touch 1 again: 2 is now the LRU
        sparse.record_loss(5, 5.0)  # evicts 2
        assert sparse.num_touched() == 4
        assert np.isinf(sparse.last_loss_for([2])[0])  # evicted -> cold
        assert sparse.last_loss_for([1])[0] == 9.0
        assert sparse.last_loss_for([5])[0] == 5.0


# --- candidate pools + partial top-k ----------------------------------------

class TestCandidatePools:
    def test_partial_top_k_matches_stable_argsort(self):
        rng = np.random.default_rng(0)
        for _ in range(20):
            scores = rng.choice([0.0, 1.0, 2.0, 3.0], size=50)  # many ties
            k = int(rng.integers(1, 20))
            full = np.argsort(-scores, kind="stable")[:k]
            np.testing.assert_array_equal(partial_top_k(scores, k), full)

    def test_pool_size_knobs(self):
        # small N, auto: no pool (bit-identical legacy path)
        assert pool_size(make_args(), 64, 8) is None
        # explicit pool engages at any N, clamped to [k, n]
        assert pool_size(make_args(selection_candidate_pool=32), 64, 8) \
            == 32
        assert pool_size(make_args(selection_candidate_pool=4), 64, 8) == 8
        assert pool_size(make_args(selection_candidate_pool=999), 64,
                         8) == 64
        # auto above the threshold: factor * k
        args = make_args(selection_pool_threshold=100,
                         selection_pool_factor=16.0)
        assert pool_size(args, 1000, 10) == 160

    def test_pooled_oort_touches_only_pool_plus_explore(self):
        """With a pool of m, one select() reads O(m) ids — pinned by
        spying on the store's id-parameterized queries."""
        args = make_args(client_selection="oort",
                         selection_candidate_pool=32,
                         client_num_in_total=10_000)
        store = SparseClientStatsStore(10_000)
        seen = []
        orig = store.rms_loss_for
        store.rms_loss_for = lambda ids: (seen.append(len(np.asarray(ids)))
                                          or orig(ids))
        strat = create_strategy(args, 10_000, store)
        sampled, _ = strat.select(3, 8)
        assert len(sampled) == len(set(sampled)) == 8
        assert max(seen) <= 32  # never a full-population read

    def test_poc_honors_pool_threshold(self):
        """power_of_choice's population-scale draw switch rides the SAME
        pool knobs as the other strategies: raising
        selection_pool_threshold above n keeps the legacy rng.choice
        draw even past the auto threshold."""
        n = FAST_SAMPLE_MIN_N
        store = SparseClientStatsStore(n)
        pinned = create_strategy(
            make_args(client_selection="power_of_choice",
                      client_num_in_total=n,
                      selection_pool_threshold=n * 10), n, store)
        auto = create_strategy(
            make_args(client_selection="power_of_choice",
                      client_num_in_total=n), n, store)
        rng = np.random.default_rng((42, 101, 3))  # (seed, _TAG_POC, r)
        legacy_cands = rng.choice(n, 16, replace=False)
        got, _ = pinned.select(3, 8)
        assert set(got) <= set(int(c) for c in legacy_cands)
        # and the auto path (threshold crossed) uses the streaming draw
        assert auto.select(3, 8) != pinned.select(3, 8)

    def test_pooled_selection_deterministic(self):
        args = make_args(client_selection="oort",
                         selection_candidate_pool=64,
                         client_num_in_total=4096)
        store = feed_observations(SparseClientStatsStore(4096), n=4096)
        a = create_strategy(args, 4096, store).select(5, 16)
        b = create_strategy(args, 4096, store).select(5, 16)
        assert a == b

    def test_full_pool_equals_legacy_path(self):
        """m == n: the pooled scorer must pick the same cohort the
        full-population argsort picks (pool membership is everyone; only
        the top-k algorithm differs)."""
        n = 64
        store = feed_observations(ClientStatsStore(n), n=n)
        legacy = create_strategy(make_args(client_selection="oort"), n,
                                 store)
        pooled = create_strategy(
            make_args(client_selection="oort", selection_candidate_pool=n),
            n, store)
        for r in range(1, 5):
            ls, _ = legacy.select(r, 8)
            ps, _ = pooled.select(r, 8)
            # explore slots ride different candidate ORDERINGS (pool is
            # a permutation), so compare the exploit sets by utility:
            # same top utilities selected
            assert sorted(ls) != [] and len(ps) == len(ls)
            u_l = legacy._utility_for(r, np.asarray(sorted(ls)))
            u_p = legacy._utility_for(r, np.asarray(sorted(ps)))
            np.testing.assert_allclose(np.sort(u_l), np.sort(u_p))


# --- streaming sampler fast path ---------------------------------------------

class TestStreamingSampler:
    def test_small_n_seeded_draws_unchanged(self):
        """Below the threshold the seeded stream must keep producing the
        exact generator.choice draws (recorded-schedule compatibility)."""
        for r in range(4):
            gen = np.random.default_rng((123, r))
            ref = [int(c) for c in gen.choice(500, 20, replace=False)]
            assert client_sampling(r, 500, 20, random_seed=123,
                                   stream="seeded") == ref

    def test_huge_n_valid_and_deterministic(self):
        n = FAST_SAMPLE_MIN_N * 4
        a = client_sampling(2, n, 100, random_seed=9, stream="seeded")
        b = client_sampling(2, n, 100, random_seed=9, stream="seeded")
        c = client_sampling(2, n, 100, random_seed=10, stream="seeded")
        assert a == b and a != c
        assert len(a) == 100 == len(set(a))
        assert all(0 <= x < n for x in a)

    def test_floyd_uniformity_and_order(self):
        """Every id equally likely, and sample ORDER is shuffled (the
        first slot is not biased toward the tail ids Floyd's loop ends
        on)."""
        n, k, trials = 40, 8, 3000
        counts = np.zeros(n)
        first = np.zeros(n)
        gen = np.random.default_rng(0)
        for _ in range(trials):
            s = sample_ids_streaming(gen, n, k)
            assert len(np.unique(s)) == k
            counts[s] += 1
            first[s[0]] += 1
        np.testing.assert_allclose(counts / trials, np.full(n, k / n),
                                   atol=0.05)
        np.testing.assert_allclose(first / trials, np.full(n, 1 / n),
                                   atol=0.02)

    def test_k_geq_n_returns_everyone(self):
        gen = np.random.default_rng(0)
        s = sample_ids_streaming(gen, 10, 15)
        assert sorted(int(c) for c in s) == list(range(10))


# --- streaming cohort assembly -----------------------------------------------

def elig_even(ids):
    return np.asarray(ids) % 2 == 0


class TestCohortAssembly:
    def _assembler(self, n=1000, **kw):
        args = make_args(client_num_in_total=n, selection_store="sparse",
                         **kw)
        store = feed_observations(SparseClientStatsStore(n), n=n)
        return StreamingCohortAssembler(args, store, n), store, args

    def test_matches_brute_force_top_k(self):
        asm, store, args = self._assembler(n=500)
        res = asm.assemble(3, 20, population_chunks(500, chunk=64))
        brute = np.argsort(-asm._score(3, np.arange(500)),
                           kind="stable")[:20]
        assert res.cohort == [int(c) for c in brute]
        assert res.scanned == 500 and res.eligible == 500
        assert len(res.cohort) == 20

    def test_chunking_independent(self):
        """The cohort is a property of (round, population, history) —
        NOT of how the candidate stream was chunked (the jitter is a
        per-id hash, not a sequential draw)."""
        asm, _, _ = self._assembler(n=700)
        a = asm.assemble(1, 25, population_chunks(700, chunk=13)).cohort
        b = asm.assemble(1, 25, population_chunks(700, chunk=512)).cohort
        assert a == b

    def test_eligibility_filters(self):
        asm, _, _ = self._assembler(n=300)
        res = asm.assemble(0, 30, population_chunks(300, chunk=50),
                           eligible_fn=elig_even)
        assert res.eligible == 150
        assert all(c % 2 == 0 for c in res.cohort)

    def test_no_eligible_returns_empty(self):
        asm, _, _ = self._assembler(n=100)
        res = asm.assemble(0, 10, population_chunks(100),
                           eligible_fn=lambda ids: np.zeros(len(ids),
                                                            bool))
        assert res.cohort == [] and res.eligible == 0

    def test_cold_start_spreads_selection(self):
        """Cold store: every candidate scores the neutral fill — the
        seeded jitter must spread the cohort instead of taking the
        lowest ids."""
        args = make_args(client_num_in_total=10_000)
        asm = StreamingCohortAssembler(args,
                                       SparseClientStatsStore(10_000),
                                       10_000)
        res = asm.assemble(0, 50, population_chunks(10_000))
        assert max(res.cohort) > 1000  # not ids 0..49
        assert len(set(res.cohort)) == 50

    def test_scoring_knob_validated(self):
        with pytest.raises(ValueError, match="cohort_scoring"):
            StreamingCohortAssembler(
                make_args(cohort_scoring="mystery"),
                SparseClientStatsStore(10), 10)


# --- deadline pacer ----------------------------------------------------------

class TestDeadlinePacer:
    def test_deterministic_given_history(self):
        history = [(8, 10, 30.0), (10, 10, 5.0), (3, 10, 60.0),
                   (10, 10, 4.0), (10, 10, 50.0)]
        a = DeadlinePacer.from_args(make_args(pacer_deadline_s=40.0))
        b = DeadlinePacer.from_args(make_args(pacer_deadline_s=40.0))
        for done, exp, wall in history:
            a.observe_round(done, exp, wall)
            b.observe_round(done, exp, wall)
        assert (a.deadline_s, a.over_sample) == (b.deadline_s,
                                                 b.over_sample)
        assert a.rounds_observed == 5

    def test_under_delivery_stretches_over_delivery_tightens(self):
        p = DeadlinePacer(deadline_s=60.0, over_sample=1.3)
        p.observe_round(2, 10, 60.0)  # 20% < target 80%
        assert p.deadline_s > 60.0 and p.over_sample > 1.3
        d, o = p.deadline_s, p.over_sample
        p.observe_round(10, 10, 5.0)  # everyone, in a fraction of T
        assert p.deadline_s < d and p.over_sample < o

    def test_bounds_hold(self):
        p = DeadlinePacer(deadline_s=60.0, max_deadline_s=100.0,
                          max_over_sample=2.0, min_deadline_s=10.0)
        for _ in range(50):
            p.observe_round(0, 10, 100.0)
        assert p.deadline_s == 100.0 and p.over_sample == 2.0
        for _ in range(200):
            p.observe_round(10, 10, 1.0)
        assert p.deadline_s >= 10.0 and p.over_sample >= 1.0

    def test_target_cohort_and_state_roundtrip(self):
        p = DeadlinePacer(over_sample=1.3)
        assert p.target_cohort(100) == 130
        assert p.target_cohort(100, ceiling=110) == 110
        p.observe_round(1, 10, 99.0)
        q = DeadlinePacer()
        q.load_state_dict(p.state_dict())
        assert (q.deadline_s, q.over_sample, q.rounds_observed) \
            == (p.deadline_s, p.over_sample, p.rounds_observed)


# --- store factory -----------------------------------------------------------

class TestStoreFactory:
    def test_auto_flips_at_threshold(self):
        args = make_args(selection_sparse_threshold=1000)
        assert isinstance(make_stats_store(args, 999), ClientStatsStore)
        assert isinstance(make_stats_store(args, 1000),
                          SparseClientStatsStore)

    def test_explicit_backends_and_validation(self):
        assert isinstance(
            make_stats_store(make_args(selection_store="sparse"), 8),
            SparseClientStatsStore)
        assert isinstance(
            make_stats_store(make_args(selection_store="dense"), 10 ** 6),
            ClientStatsStore)
        with pytest.raises(ValueError, match="selection_store"):
            make_stats_store(make_args(selection_store="csr"), 8)

    def test_manager_rides_sparse_backend(self):
        args = make_args(client_selection="oort", selection_store="sparse",
                         client_num_in_total=128)
        mgr = SelectionManager(args, 128)
        assert isinstance(mgr.store, SparseClientStatsStore)
        sampled, excluded = mgr.select(0, 8)
        assert len(sampled) == 8 and excluded == []


# --- SP simulator selection seam (the PR 3/5 gap) ---------------------------

class TestSPSelection:
    def _run(self, **kw):
        import fedml_tpu
        base = dict(client_num_in_total=12, client_num_per_round=4,
                    comm_round=6, frequency_of_the_test=100)
        base.update(kw)
        return fedml_tpu.run_simulation(backend="sp", args=make_args(**base))

    def test_oort_on_sp_records_history(self):
        import fedml_tpu
        from fedml_tpu import data as data_mod, model as model_mod
        from fedml_tpu.core.algframe.client_trainer import \
            ClassificationTrainer
        from fedml_tpu.optimizers.registry import create_optimizer
        from fedml_tpu.simulation.sp.simulator import SPSimulator
        args = make_args(client_num_in_total=12, client_num_per_round=4,
                         comm_round=6, client_selection="oort",
                         frequency_of_the_test=100)
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        spec = ClassificationTrainer(bundle.apply)
        sim = SPSimulator(args, fed, bundle,
                          create_optimizer(args, spec), spec)
        assert sim.selection.track
        sim.run()
        st = sim.selection.store
        assert st.num_touched() > 0
        assert int(np.sum(st.times_selected_for(np.arange(12)))) == 6 * 4

    def test_sp_crash_resume_replays_selections(self, tmp_path):
        """Selection history rides the SP checkpoint: a run cut short
        after round 3 (the SP loop has no chaos plan — truncation IS the
        crash) must resume into the SAME rounds 4-5 trajectory as the
        uninterrupted run, which requires replaying identical cohorts."""
        kw = dict(client_num_in_total=12, client_num_per_round=4,
                  client_selection="power_of_choice", comm_round=6,
                  checkpoint_every_rounds=2, frequency_of_the_test=100)
        a = self._run(checkpoint_dir=str(tmp_path / "a"), **kw)
        self._run(checkpoint_dir=str(tmp_path / "b"),
                  **dict(kw, comm_round=4))  # "crashes" after round 3
        b = self._run(checkpoint_dir=str(tmp_path / "b"), **kw)
        # identical selection history => a manager rebuilt from either
        # run selects identical future cohorts
        import jax
        for x, y in zip(jax.tree_util.tree_leaves(a["params"]),
                        jax.tree_util.tree_leaves(b["params"])):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-4, atol=1e-6)

    def test_sp_default_has_passive_selection(self):
        from fedml_tpu import data as data_mod, model as model_mod
        from fedml_tpu.core.algframe.client_trainer import \
            ClassificationTrainer
        from fedml_tpu.optimizers.registry import create_optimizer
        from fedml_tpu.simulation.sp.simulator import SPSimulator
        args = make_args()
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        spec = ClassificationTrainer(bundle.apply)
        sim = SPSimulator(args, fed, bundle,
                          create_optimizer(args, spec), spec)
        assert not sim.selection.track
        assert "selection" not in sim._ckpt_state()


# --- 1M-client smoke (slow gate) --------------------------------------------

@pytest.mark.slow
class TestMillionClientSmoke:
    def test_assemble_and_select_at_1m(self):
        """1M synthetic devices: sparse store + pooled oort select +
        one full streaming assembly, all bounded — and selection cost
        must not scale with the population (the ISSUE 15 acceptance
        shape, asserted loosely here; the bench records the numbers)."""
        import time as _time
        n = 1_000_000
        args = make_args(client_num_in_total=n, selection_store="sparse",
                         client_selection="oort",
                         sampling_stream="seeded")
        mgr = SelectionManager(args, n)
        assert isinstance(mgr.store, SparseClientStatsStore)
        feed_observations(mgr.store, n=n, rounds=4, k=64)
        t0 = _time.perf_counter()
        for r in range(3):
            sampled, _ = mgr.select(r, 128)
            assert len(sampled) == len(set(sampled)) == 128
        select_s = (_time.perf_counter() - t0) / 3
        assert select_s < 1.0, f"pooled select took {select_s:.2f}s at 1M"
        asm = StreamingCohortAssembler(args, mgr.store, n)
        t0 = _time.perf_counter()
        res = asm.assemble(0, 256, population_chunks(n),
                           eligible_fn=elig_even)
        wall = _time.perf_counter() - t0
        assert len(res.cohort) == 256 and res.scanned == n
        assert all(c % 2 == 0 for c in res.cohort)
        assert wall < 30.0, f"1M assembly took {wall:.1f}s"
