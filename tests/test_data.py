"""Data zoo: partitioners, LEAF natural partitions, reference-style
synthetic(alpha, beta), multilabel task plumbing."""

import json
import os

import numpy as np
import pytest

from fedml_tpu import data as data_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.data.noniid_partition import (hetero_dirichlet_partition,
                                                  partition, shard_partition)


class TestPartitioners:
    def test_homo_covers_all(self):
        parts = partition(np.arange(100) % 10, 7, "homo")
        all_idx = np.concatenate([parts[i] for i in range(7)])
        assert sorted(all_idx.tolist()) == list(range(100))

    def test_dirichlet_skews_labels(self):
        rng = np.random.RandomState(0)
        labels = rng.randint(0, 10, 5000)
        parts = hetero_dirichlet_partition(labels, 10, alpha=0.1,
                                           rng=np.random.RandomState(1))
        all_idx = np.concatenate([parts[i] for i in range(10)])
        assert sorted(all_idx.tolist()) == list(range(5000))
        # low alpha -> strong skew: some client has a dominant class
        shares = []
        for i in range(10):
            counts = np.bincount(labels[parts[i]], minlength=10)
            shares.append(counts.max() / max(counts.sum(), 1))
        assert max(shares) > 0.5

    def test_shard_partition_limits_classes(self):
        labels = np.repeat(np.arange(10), 100)
        parts = shard_partition(labels, 10, shards_per_client=2,
                                rng=np.random.RandomState(0))
        all_idx = np.concatenate([parts[i] for i in range(10)])
        assert sorted(all_idx.tolist()) == list(range(1000))
        classes_per_client = [len(np.unique(labels[parts[i]]))
                              for i in range(10)]
        assert max(classes_per_client) <= 3  # ~2 shards -> <=3 classes


class TestLoaders:
    def test_synthetic_federated_natural_partition(self):
        args = Arguments(dataset="synthetic_1_1", client_num_in_total=6,
                         batch_size=16)
        fed, out_dim = data_mod.load(args)
        assert out_dim == 10
        assert fed.num_clients == 6
        # the Li-et-al generator is 60-feature (unlike the MNIST fallback's
        # 784) and produces heterogeneous client sizes
        assert fed.input_shape == (60,)
        assert fed.client_num_samples.std() > 0

    def test_stackoverflow_lr_multilabel(self):
        args = Arguments(dataset="stackoverflow_lr", allow_synthetic=True, client_num_in_total=4,
                         batch_size=16)
        fed, out_dim = data_mod.load(args)
        assert fed.task == "multilabel"
        assert fed.train.y.ndim == 4  # [clients, nb, bs, tags]
        assert out_dim == fed.train.y.shape[-1]

    def test_leaf_reader(self, tmp_path):
        root = tmp_path / "femnist"
        (root / "train").mkdir(parents=True)
        rng = np.random.RandomState(0)
        blob = {"users": ["u0", "u1"],
                "num_samples": [30, 20],
                "user_data": {
                    "u0": {"x": rng.rand(30, 784).tolist(),
                           "y": rng.randint(0, 62, 30).tolist()},
                    "u1": {"x": rng.rand(20, 784).tolist(),
                           "y": rng.randint(0, 62, 20).tolist()}}}
        with open(root / "train" / "all_data.json", "w") as f:
            json.dump(blob, f)
        args = Arguments(dataset="femnist", client_num_in_total=2,
                         batch_size=8, data_cache_dir=str(tmp_path))
        fed, out_dim = data_mod.load(args)
        assert out_dim == 62
        assert fed.num_clients == 2
        assert fed.client_num_samples.tolist() == [27, 18]  # 10% held out


class TestRealDataPolicy:
    """Strict real-data policy: synthetic stand-ins are opt-in and labeled."""

    def test_bundled_real_digits(self, tmp_path):
        # digits ships inside scikit-learn: real data with zero egress
        args = Arguments(dataset="digits", model="cnn",
                         client_num_in_total=4, batch_size=16,
                         data_cache_dir=str(tmp_path))
        fed, out_dim = data_mod.load(args)
        assert fed.provenance == "real"
        assert out_dim == 10
        assert fed.input_shape == (8, 8, 1)
        assert fed.total_train_samples > 1000
        # second load hits the npz cache
        assert (tmp_path / "digits.npz").exists()

    def test_bundled_real_tabular(self, tmp_path):
        args = Arguments(dataset="wine", client_num_in_total=3, batch_size=8,
                         data_cache_dir=str(tmp_path))
        fed, out_dim = data_mod.load(args)
        assert fed.provenance == "real"
        assert out_dim == 3

    def test_missing_real_dataset_raises(self, tmp_path, monkeypatch):
        monkeypatch.delenv("FEDML_TPU_ALLOW_SYNTHETIC", raising=False)
        # keep the test hermetic on network-connected machines
        from fedml_tpu.data import acquire as acquire_mod
        monkeypatch.setattr(acquire_mod, "acquire", lambda *a, **k: None)
        args = Arguments(dataset="cifar10", data_cache_dir=str(tmp_path))
        with pytest.raises(FileNotFoundError):
            data_mod.load(args)

    def test_synthetic_optin_is_labeled(self, tmp_path):
        args = Arguments(dataset="cifar10", data_cache_dir=str(tmp_path),
                         allow_synthetic=True, model="simple_cnn")
        fed, _ = data_mod.load(args)
        assert fed.provenance == "synthetic"

    def test_real_digits_learns(self, tmp_path):
        """Honest real-data accuracy: federated LR on UCI digits beats 80%
        within a few rounds (10-class task, 10% chance level)."""
        import fedml_tpu
        args = Arguments(dataset="digits", model="lr",
                         client_num_in_total=8, client_num_per_round=8,
                         comm_round=10, epochs=2, batch_size=32,
                         learning_rate=0.3, frequency_of_the_test=9,
                         data_cache_dir=str(tmp_path), random_seed=0)
        r = fedml_tpu.run_simulation(backend="tpu", args=args)
        assert r["final_test_acc"] > 0.8
