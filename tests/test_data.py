"""Data zoo: partitioners, LEAF natural partitions, reference-style
synthetic(alpha, beta), multilabel task plumbing."""

import json
import os

import numpy as np
import pytest

from fedml_tpu import data as data_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.data.noniid_partition import (hetero_dirichlet_partition,
                                                  partition, shard_partition)


class TestPartitioners:
    def test_homo_covers_all(self):
        parts = partition(np.arange(100) % 10, 7, "homo")
        all_idx = np.concatenate([parts[i] for i in range(7)])
        assert sorted(all_idx.tolist()) == list(range(100))

    def test_dirichlet_skews_labels(self):
        rng = np.random.RandomState(0)
        labels = rng.randint(0, 10, 5000)
        parts = hetero_dirichlet_partition(labels, 10, alpha=0.1,
                                           rng=np.random.RandomState(1))
        all_idx = np.concatenate([parts[i] for i in range(10)])
        assert sorted(all_idx.tolist()) == list(range(5000))
        # low alpha -> strong skew: some client has a dominant class
        shares = []
        for i in range(10):
            counts = np.bincount(labels[parts[i]], minlength=10)
            shares.append(counts.max() / max(counts.sum(), 1))
        assert max(shares) > 0.5

    def test_shard_partition_limits_classes(self):
        labels = np.repeat(np.arange(10), 100)
        parts = shard_partition(labels, 10, shards_per_client=2,
                                rng=np.random.RandomState(0))
        all_idx = np.concatenate([parts[i] for i in range(10)])
        assert sorted(all_idx.tolist()) == list(range(1000))
        classes_per_client = [len(np.unique(labels[parts[i]]))
                              for i in range(10)]
        assert max(classes_per_client) <= 3  # ~2 shards -> <=3 classes


class TestLoaders:
    def test_synthetic_federated_natural_partition(self):
        args = Arguments(dataset="synthetic_1_1", client_num_in_total=6,
                         batch_size=16)
        fed, out_dim = data_mod.load(args)
        assert out_dim == 10
        assert fed.num_clients == 6
        # the Li-et-al generator is 60-feature (unlike the MNIST fallback's
        # 784) and produces heterogeneous client sizes
        assert fed.input_shape == (60,)
        assert fed.client_num_samples.std() > 0

    def test_stackoverflow_lr_multilabel(self):
        args = Arguments(dataset="stackoverflow_lr", client_num_in_total=4,
                         batch_size=16)
        fed, out_dim = data_mod.load(args)
        assert fed.task == "multilabel"
        assert fed.train.y.ndim == 4  # [clients, nb, bs, tags]
        assert out_dim == fed.train.y.shape[-1]

    def test_leaf_reader(self, tmp_path):
        root = tmp_path / "femnist"
        (root / "train").mkdir(parents=True)
        rng = np.random.RandomState(0)
        blob = {"users": ["u0", "u1"],
                "num_samples": [30, 20],
                "user_data": {
                    "u0": {"x": rng.rand(30, 784).tolist(),
                           "y": rng.randint(0, 62, 30).tolist()},
                    "u1": {"x": rng.rand(20, 784).tolist(),
                           "y": rng.randint(0, 62, 20).tolist()}}}
        with open(root / "train" / "all_data.json", "w") as f:
            json.dump(blob, f)
        args = Arguments(dataset="femnist", client_num_in_total=2,
                         batch_size=8, data_cache_dir=str(tmp_path))
        fed, out_dim = data_mod.load(args)
        assert out_dim == 62
        assert fed.num_clients == 2
        assert fed.client_num_samples.tolist() == [27, 18]  # 10% held out
