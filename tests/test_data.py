"""Data zoo: partitioners, LEAF natural partitions, reference-style
synthetic(alpha, beta), multilabel task plumbing."""

import json
import os

import numpy as np
import pytest

from fedml_tpu import data as data_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.data.noniid_partition import (hetero_dirichlet_partition,
                                                  partition, shard_partition)


class TestPartitioners:
    def test_homo_covers_all(self):
        parts = partition(np.arange(100) % 10, 7, "homo")
        all_idx = np.concatenate([parts[i] for i in range(7)])
        assert sorted(all_idx.tolist()) == list(range(100))

    def test_dirichlet_skews_labels(self):
        rng = np.random.RandomState(0)
        labels = rng.randint(0, 10, 5000)
        parts = hetero_dirichlet_partition(labels, 10, alpha=0.1,
                                           rng=np.random.RandomState(1))
        all_idx = np.concatenate([parts[i] for i in range(10)])
        assert sorted(all_idx.tolist()) == list(range(5000))
        # low alpha -> strong skew: some client has a dominant class
        shares = []
        for i in range(10):
            counts = np.bincount(labels[parts[i]], minlength=10)
            shares.append(counts.max() / max(counts.sum(), 1))
        assert max(shares) > 0.5

    def test_shard_partition_limits_classes(self):
        labels = np.repeat(np.arange(10), 100)
        parts = shard_partition(labels, 10, shards_per_client=2,
                                rng=np.random.RandomState(0))
        all_idx = np.concatenate([parts[i] for i in range(10)])
        assert sorted(all_idx.tolist()) == list(range(1000))
        classes_per_client = [len(np.unique(labels[parts[i]]))
                              for i in range(10)]
        assert max(classes_per_client) <= 3  # ~2 shards -> <=3 classes


class TestLoaders:
    def test_synthetic_federated_natural_partition(self):
        args = Arguments(dataset="synthetic_1_1", client_num_in_total=6,
                         batch_size=16)
        fed, out_dim = data_mod.load(args)
        assert out_dim == 10
        assert fed.num_clients == 6
        # the Li-et-al generator is 60-feature (unlike the MNIST fallback's
        # 784) and produces heterogeneous client sizes
        assert fed.input_shape == (60,)
        assert fed.client_num_samples.std() > 0

    def test_stackoverflow_lr_multilabel(self):
        args = Arguments(dataset="stackoverflow_lr", allow_synthetic=True, client_num_in_total=4,
                         batch_size=16)
        fed, out_dim = data_mod.load(args)
        assert fed.task == "multilabel"
        assert fed.train.y.ndim == 4  # [clients, nb, bs, tags]
        assert out_dim == fed.train.y.shape[-1]

    def test_leaf_reader(self, tmp_path):
        root = tmp_path / "femnist"
        (root / "train").mkdir(parents=True)
        rng = np.random.RandomState(0)
        blob = {"users": ["u0", "u1"],
                "num_samples": [30, 20],
                "user_data": {
                    "u0": {"x": rng.rand(30, 784).tolist(),
                           "y": rng.randint(0, 62, 30).tolist()},
                    "u1": {"x": rng.rand(20, 784).tolist(),
                           "y": rng.randint(0, 62, 20).tolist()}}}
        with open(root / "train" / "all_data.json", "w") as f:
            json.dump(blob, f)
        args = Arguments(dataset="femnist", client_num_in_total=2,
                         batch_size=8, data_cache_dir=str(tmp_path))
        fed, out_dim = data_mod.load(args)
        assert out_dim == 62
        assert fed.num_clients == 2
        assert fed.client_num_samples.tolist() == [27, 18]  # 10% held out


class TestRealDataPolicy:
    """Strict real-data policy: synthetic stand-ins are opt-in and labeled."""

    def test_bundled_real_digits(self, tmp_path):
        # digits ships inside scikit-learn: real data with zero egress
        args = Arguments(dataset="digits", model="cnn",
                         client_num_in_total=4, batch_size=16,
                         data_cache_dir=str(tmp_path))
        fed, out_dim = data_mod.load(args)
        assert fed.provenance == "real"
        assert out_dim == 10
        assert fed.input_shape == (8, 8, 1)
        assert fed.total_train_samples > 1000
        # second load hits the npz cache
        assert (tmp_path / "digits.npz").exists()

    def test_bundled_real_tabular(self, tmp_path):
        args = Arguments(dataset="wine", client_num_in_total=3, batch_size=8,
                         data_cache_dir=str(tmp_path))
        fed, out_dim = data_mod.load(args)
        assert fed.provenance == "real"
        assert out_dim == 3

    def test_missing_real_dataset_raises(self, tmp_path, monkeypatch):
        monkeypatch.delenv("FEDML_TPU_ALLOW_SYNTHETIC", raising=False)
        # keep the test hermetic on network-connected machines
        from fedml_tpu.data import acquire as acquire_mod
        monkeypatch.setattr(acquire_mod, "acquire", lambda *a, **k: None)
        args = Arguments(dataset="cifar10", data_cache_dir=str(tmp_path))
        with pytest.raises(FileNotFoundError):
            data_mod.load(args)

    def test_offline_archive_import(self, tmp_path, monkeypatch):
        """A raw cifar-10-python.tar.gz dropped in $FEDML_TPU_OFFLINE_DIR
        is parsed with NO network and flips the dataset to real — the
        airgapped path that makes the flagship bench real-data when the
        operator provides the archive (VERDICT r3 item 2c)."""
        import io
        import pickle
        import tarfile

        rng = np.random.RandomState(0)
        offline = tmp_path / "offline"
        offline.mkdir()

        def batch(n):
            return {b"data": rng.randint(0, 256, (n, 3072), np.uint8),
                    b"labels": rng.randint(0, 10, n).tolist()}

        tar_path = offline / "cifar-10-python.tar.gz"
        with tarfile.open(tar_path, "w:gz") as tf:
            for name, n in (("data_batch_1", 64), ("data_batch_2", 64),
                            ("test_batch", 32)):
                blob = pickle.dumps(batch(n))
                info = tarfile.TarInfo(f"cifar-10-batches-py/{name}")
                info.size = len(blob)
                tf.addfile(info, io.BytesIO(blob))

        monkeypatch.setenv("FEDML_TPU_OFFLINE_DIR", str(offline))
        cache = tmp_path / "cache"
        args = Arguments(dataset="cifar10", model="simple_cnn",
                         client_num_in_total=4, client_num_per_round=4,
                         batch_size=8, data_cache_dir=str(cache))
        fed, out = data_mod.load(args)
        assert out == 10 and fed.provenance == "real"
        assert (cache / "cifar10.npz").exists()
        x = np.asarray(fed.train.x)
        assert x.shape[-3:] == (32, 32, 3)

    def test_synthetic_optin_is_labeled(self, tmp_path):
        args = Arguments(dataset="cifar10", data_cache_dir=str(tmp_path),
                         allow_synthetic=True, model="simple_cnn")
        fed, _ = data_mod.load(args)
        assert fed.provenance == "synthetic"

    def test_real_digits_learns(self, tmp_path):
        """Honest real-data accuracy: federated LR on UCI digits beats 80%
        within a few rounds (10-class task, 10% chance level)."""
        import fedml_tpu
        args = Arguments(dataset="digits", model="lr",
                         client_num_in_total=8, client_num_per_round=8,
                         comm_round=10, epochs=2, batch_size=32,
                         learning_rate=0.3, frequency_of_the_test=9,
                         data_cache_dir=str(tmp_path), random_seed=0)
        r = fedml_tpu.run_simulation(backend="tpu", args=args)
        assert r["final_test_acc"] > 0.8


class TestBundledShakespeare:
    def test_mini_shakespeare_materializes_and_loads(self, tmp_path):
        """Bundled REAL Shakespeare -> LEAF JSON -> LEAF reader: client =
        speaking role, x/y = 80-char windows shifted by one."""
        from fedml_tpu.arguments import Arguments
        from fedml_tpu import data as data_mod
        args = Arguments(dataset="shakespeare", model="rnn",
                         client_num_in_total=10, batch_size=16,
                         data_cache_dir=str(tmp_path))
        fed, n_classes = data_mod.load(args)
        assert getattr(fed, "provenance", "real") == "real"
        assert n_classes == 90
        assert fed.num_clients == 10  # one client per role
        x = np.asarray(fed.train.x)
        y = np.asarray(fed.train.y)
        assert x.shape[-1] == 80 and y.shape[-1] == 80
        # y is x shifted by one character wherever both are real text
        m = np.asarray(fed.train.mask)[0].reshape(-1) > 0
        xf = x[0].reshape(-1, 80)[m]
        yf = y[0].reshape(-1, 80)[m]
        np.testing.assert_array_equal(xf[0, 1:], yf[0, :-1])
        # the LEAF dir was materialized on disk in the cache
        assert (tmp_path / "bundled" / "shakespeare" / "train").is_dir()


class TestTFFFormats:
    """The reference's TFF HDF5 on-disk formats load from a local cache —
    checked-in fixtures (scripts/make_fixtures.py) pin the exact layout
    (reference data/fed_cifar100/data_loader.py:1-202,
    data/stackoverflow_nwp/data_loader.py:1-207, data/stackoverflow_lr/)."""

    FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")

    def _args(self, name, model, n):
        return Arguments(dataset=name, model=model, client_num_in_total=n,
                         client_num_per_round=n, comm_round=1, epochs=1,
                         batch_size=8, learning_rate=0.1, random_seed=0,
                         data_cache_dir=self.FIXTURES)

    def test_fed_cifar100_h5(self):
        fed, out = data_mod.load(self._args("fed_cifar100", "resnet18", 4))
        assert out == 100 and fed.provenance == "real"
        assert fed.num_clients == 4
        x = np.asarray(fed.train.x)
        assert x.shape[-3:] == (32, 32, 3) and 0.0 <= x.min() <= x.max() <= 1.0

    def test_stackoverflow_nwp_h5(self):
        fed, out = data_mod.load(self._args("stackoverflow_nwp", "rnn", 4))
        assert fed.provenance == "real" and fed.num_clients == 4
        x = np.asarray(fed.train.x)
        y = np.asarray(fed.train.y)
        assert x.shape[-1] == 20 and y.shape[-1] == 20
        # next-word labels: y is x shifted by one on real rows
        m = np.asarray(fed.train.mask)[0].reshape(-1) > 0
        xf, yf = x[0].reshape(-1, 20)[m], y[0].reshape(-1, 20)[m]
        np.testing.assert_array_equal(xf[0, 1:], yf[0, :-1])
        assert xf[0, 0] == out - 3  # bos = len(vocab) - 2 of vocab+oov ids

    def test_stackoverflow_lr_h5(self):
        fed, out = data_mod.load(self._args("stackoverflow_lr", "lr", 4))
        assert out == 8 and fed.provenance == "real"  # fixture tag count
        x = np.asarray(fed.train.x)
        y = np.asarray(fed.train.y)
        # bag-of-words rows sum to <= 1 (mean one-hot, oov column dropped)
        m = np.asarray(fed.train.mask)[0].reshape(-1) > 0
        rows = x[0].reshape(-1, x.shape[-1])[m]
        assert rows.sum(-1).max() <= 1.0 + 1e-6
        assert set(np.unique(y)) <= {0.0, 1.0}

    def test_stackoverflow_lr_trains_one_round(self):
        import fedml_tpu
        args = self._args("stackoverflow_lr", "lr", 4)
        r = fedml_tpu.run_simulation(backend="sp", args=args)
        assert "final_test_acc" in r


class TestLeafReddit:
    def test_reddit_leaf_cache_loads_real(self, tmp_path):
        """A LEAF-format reddit cache (users/user_data json — the layout
        the reference's LEAF-derived loaders read) loads through the
        standard dispatch as a REAL sequence dataset with the natural
        per-user partition (reference data/reddit/data_loader.py:1-141;
        that loader's albert tokenizer needs a model download, so the
        LEAF text route is the zero-egress path here)."""
        root = tmp_path / "reddit" / "train"
        root.mkdir(parents=True)
        blob = {"users": [], "num_samples": [], "user_data": {}}
        for u in range(3):
            name = f"redditor_{u}"
            posts = [f"post {i} from user {u} about jax" for i in range(6)]
            nxt = [p[1:] + "x" for p in posts]  # next-char style labels
            blob["users"].append(name)
            blob["num_samples"].append(len(posts))
            blob["user_data"][name] = {"x": posts, "y": nxt}
        with open(root / "data.json", "w") as f:
            json.dump(blob, f)
        args = Arguments(dataset="reddit", model="rnn",
                         client_num_in_total=3, client_num_per_round=3,
                         comm_round=1, epochs=1, batch_size=4,
                         learning_rate=0.1, random_seed=0,
                         data_cache_dir=str(tmp_path))
        fed, out = data_mod.load(args)
        assert fed.num_clients == 3
        assert getattr(fed, "provenance", "real") == "real"
        x = np.asarray(fed.train.x)
        assert x.dtype == np.int32 and x.ndim == 4  # [c, nb, bs, L] tokens


class TestImageDirectoryLoaders:
    """ImageNet folder trees and Landmarks CSV-mapped user partitions load
    from a local cache (reference data/ImageNet/data_loader.py:1-411,
    data/Landmarks/data_loader.py:123-151). Fixtures are tiny real JPEGs
    generated in-test (PIL round-trips actual image decoding)."""

    @staticmethod
    def _write_img(path, rgb, size=32):
        from PIL import Image
        arr = np.full((size, size, 3), rgb, np.uint8)
        Image.fromarray(arr).save(path)

    def test_imagenet_folder_tree(self, tmp_path):
        import fedml_tpu
        root = tmp_path / "imagenet"
        rng = np.random.RandomState(0)
        for split, n in (("train", 8), ("val", 3)):
            for ci, wnid in enumerate(["n01440764", "n01443537"]):
                d = root / split / wnid
                d.mkdir(parents=True, exist_ok=True)
                for i in range(n):
                    self._write_img(str(d / f"img_{i}.JPEG"),
                                    rng.randint(0, 255, 3))
        args = Arguments(dataset="imagenet", model="cnn",
                         client_num_in_total=4, client_num_per_round=4,
                         comm_round=1, epochs=1, batch_size=4,
                         learning_rate=0.1, random_seed=0,
                         partition_method="homo",
                         data_cache_dir=str(tmp_path))
        fed, out = data_mod.load(args)
        assert out == 2 and fed.provenance == "real"
        assert fed.num_clients == 4
        x = np.asarray(fed.train.x)
        assert x.shape[-3:] == (64, 64, 3)
        assert 0.0 <= x.min() <= x.max() <= 1.0

    def test_landmarks_user_partition(self, tmp_path):
        root = tmp_path / "gld23k"
        (root / "images").mkdir(parents=True)
        rng = np.random.RandomState(1)
        rows = []
        for u in range(3):
            for i in range(4):
                img_id = f"u{u}_img{i}"
                self._write_img(str(root / "images" / f"{img_id}.jpg"),
                                rng.randint(0, 255, 3))
                rows.append((f"user_{u}", img_id, f"class_{i % 2}"))
        with open(root / "federated_train.csv", "w") as f:
            f.write("user_id,image_id,class\n")
            for r in rows:
                f.write(",".join(r) + "\n")
        args = Arguments(dataset="gld23k", model="cnn",
                         client_num_in_total=3, client_num_per_round=3,
                         comm_round=1, epochs=1, batch_size=4,
                         learning_rate=0.1, random_seed=0,
                         data_cache_dir=str(tmp_path))
        fed, out = data_mod.load(args)
        assert out == 2 and fed.provenance == "real"
        assert fed.num_clients == 3  # natural per-user partition
        # held-out test split (no test.csv): one image per user
        assert np.asarray(fed.test["x"]).reshape(-1, 64, 64, 3).shape[0] >= 3


class TestFinanceLoaders:
    def test_lending_club_from_cache(self, tmp_path):
        """A cached loan.csv with the reference schema loads as real."""
        import csv as _csv
        from fedml_tpu.arguments import Arguments
        from fedml_tpu import data as data_mod
        from fedml_tpu.data.finance import LENDING_CLUB_FEATURES
        d = tmp_path / "lending_club"
        d.mkdir()
        rng = np.random.RandomState(0)
        with open(d / "loan.csv", "w", newline="") as f:
            w = _csv.writer(f)
            w.writerow(list(LENDING_CLUB_FEATURES) + ["loan_status"])
            for i in range(600):
                row = [f"{v:.3f}" for v in rng.randn(
                    len(LENDING_CLUB_FEATURES))]
                w.writerow(row + (["Fully Paid"] if i % 3 else
                                  ["Charged Off"]))
        args = Arguments(dataset="lending_club", model="lr",
                         client_num_in_total=4, batch_size=32,
                         data_cache_dir=str(tmp_path))
        fed, n_classes = data_mod.load(args)
        assert fed.provenance == "real"
        assert n_classes == 2
        assert np.asarray(fed.train.x).shape[-1] == len(
            LENDING_CLUB_FEATURES)

    def test_nus_wide_synthetic_feeds_vertical_fl(self):
        """The two-block NUS-WIDE stand-in trains a 2-party vertical FL
        model better than either party could alone (label depends on both
        blocks)."""
        import fedml_tpu
        from fedml_tpu.arguments import Arguments
        args = Arguments(dataset="nus_wide", model="lr",
                         federated_optimizer="vfl", party_num=2,
                         client_num_in_total=2, client_num_per_round=2,
                         comm_round=25, batch_size=64, learning_rate=0.1,
                         random_seed=0, allow_synthetic=True,
                         frequency_of_the_test=5)
        r = fedml_tpu.run_simulation(backend="sp", args=args)
        assert r["final_test_acc"] > 0.5, r["history"][-3:]


class TestEdgeCaseBackdoor:
    def test_edge_case_attack_raises_asr(self):
        """Edge-case poisoning (reference data/edge_case_examples shape):
        byzantine clients train transformed source-class samples with the
        TARGET label; the poisoned global model's attack success rate on
        HELD-OUT edge cases rises well above the clean model's, while main
        accuracy survives."""
        import jax
        import jax.numpy as jnp
        import fedml_tpu
        from fedml_tpu.arguments import Arguments
        from fedml_tpu import data as data_mod, model as model_mod
        from fedml_tpu.data.edge_case import (attack_success_rate,
                                              build_edge_case_set,
                                              inject_edge_cases)

        def run(poison):
            args = Arguments(dataset="digits", model="lr",
                             client_num_in_total=8, client_num_per_round=8,
                             comm_round=10, batch_size=32,
                             learning_rate=0.3, random_seed=1,
                             frequency_of_the_test=5)
            fed, output_dim = data_mod.load(args)
            bundle = model_mod.create(args, output_dim)
            x_all = np.asarray(fed.train.x).reshape(
                (-1,) + np.asarray(fed.train.x).shape[3:])
            y_all = np.asarray(fed.train.y).reshape(-1)
            m_all = np.asarray(fed.train.mask).reshape(-1) > 0
            edge = build_edge_case_set(x_all[m_all], y_all[m_all],
                                       source_label=7, target_label=2)
            if poison:
                byz = np.zeros(fed.num_clients)
                byz[:3] = 1.0
                fed = inject_edge_cases(fed, edge, byz)
            from fedml_tpu.core.algframe.client_trainer import (
                ClassificationTrainer)
            from fedml_tpu.optimizers.registry import create_optimizer
            from fedml_tpu.simulation.tpu.engine import TPUSimulator
            spec = ClassificationTrainer(bundle.apply)
            sim = TPUSimulator(args, fed, bundle,
                               create_optimizer(args, spec), spec)
            out = sim.run(comm_round=10)

            def predict(x):
                logits = bundle.apply(out["params"], jnp.asarray(x))
                return np.asarray(jnp.argmax(logits, -1))

            return (attack_success_rate(predict, edge),
                    out["final_test_acc"])

        asr_clean, acc_clean = run(poison=False)
        asr_poisoned, acc_poisoned = run(poison=True)
        assert asr_poisoned > asr_clean + 0.3, (asr_clean, asr_poisoned)
        assert acc_poisoned > acc_clean - 0.1, (acc_clean, acc_poisoned)


class TestFedNLPFormat:
    """Reader for the reference FedNLP h5 pair (VERDICT r4 missing #7):
    attributes JSON + X/<idx>, Y/<idx> datasets; partition file with
    <method>/partition_data/<client>/{train,test} index lists — the exact
    layout base_raw_data_loader.py:38-45 writes."""

    def _write_fixture(self, d):
        import h5py
        import json as _json
        texts = ["the cat sat", "stocks rallied", "goal scored late",
                 "rain tomorrow", "new phone launch", "court ruling"]
        labels = ["pets", "finance", "sports", "weather", "tech", "law"]
        with h5py.File(d / "tiny_data.h5", "w") as f:
            f["attributes"] = _json.dumps({
                "task_type": "text_classification", "num_labels": 6,
                "label_vocab": {l: i for i, l in enumerate(sorted(
                    set(labels)))}})
            for i, (x, y) in enumerate(zip(texts, labels)):
                f[f"X/{i}"] = x
                f[f"Y/{i}"] = y
        with h5py.File(d / "tiny_partition.h5", "w") as f:
            g = f.create_group("uniform")
            g["n_clients"] = 2
            pd = g.create_group("partition_data")
            pd.create_group("0")["train"] = [0, 1]
            pd["0"]["test"] = [2]
            pd.create_group("1")["train"] = [3, 4]
            pd["1"]["test"] = [5]

    def test_load_exact_reference_layout(self, tmp_path):
        from fedml_tpu.data.fednlp_h5 import load_fednlp_text_classification
        d = tmp_path / "fednlp_tiny"
        d.mkdir()
        self._write_fixture(d)
        fed, n_labels = load_fednlp_text_classification(str(d), batch_size=2)
        assert n_labels == 6
        assert fed.num_clients == 2
        assert fed.provenance == "real"
        # byte tokenization: fixed length, 0-padded, +1 offset
        import numpy as np
        x00 = np.asarray(fed.train.x[0, 0])
        assert x00.shape[-1] == 128
        assert x00.dtype == np.int32
        # test split pooled from per-client test indices
        assert int(np.asarray(fed.test["mask"]).sum()) == 2

    def test_empty_client_and_missing_partition_method(self, tmp_path):
        """A client with an empty train list must load (sparse niid
        partitions do this), and a requested partition method absent
        from the file falls back with a warning, not a KeyError."""
        import h5py
        import json as _json
        from fedml_tpu.data.fednlp_h5 import load_fednlp_text_classification
        d = tmp_path / "fednlp_sparse"
        d.mkdir()
        with h5py.File(d / "t_data.h5", "w") as f:
            f["attributes"] = _json.dumps({"num_labels": 2,
                                           "label_vocab": {"a": 0, "b": 1}})
            for i in range(4):
                f[f"X/{i}"] = f"text {i}"
                f[f"Y/{i}"] = "a" if i % 2 else "b"
        with h5py.File(d / "t_partition.h5", "w") as f:
            g = f.create_group("niid")
            g["n_clients"] = 2
            pd = g.create_group("partition_data")
            pd.create_group("0")["train"] = [0, 1, 2]
            pd["0"]["test"] = [3]
            pd.create_group("1")["train"] = []       # empty client
            pd["1"]["test"] = []
        fed, n = load_fednlp_text_classification(
            str(d), batch_size=2, partition_method="uniform")  # absent
        assert n == 2 and fed.num_clients == 2

    def test_incomplete_label_vocab_extends_instead_of_keyerror(
            self, tmp_path):
        """A declared vocab missing labels present in Y (partial/corrupt
        cache) must not KeyError: undeclared labels get fresh ids past the
        declared ones and num_labels widens to fit them."""
        import h5py
        import json as _json
        import numpy as np
        from fedml_tpu.data.fednlp_h5 import load_fednlp_text_classification
        d = tmp_path / "fednlp_partial"
        d.mkdir()
        with h5py.File(d / "t_data.h5", "w") as f:
            f["attributes"] = _json.dumps({
                "num_labels": 2, "label_vocab": {"a": 0, "b": 1}})
            for i, lab in enumerate(["a", "b", "c", "c"]):  # c undeclared
                f[f"X/{i}"] = f"text {i}"
                f[f"Y/{i}"] = lab
        with h5py.File(d / "t_partition.h5", "w") as f:
            g = f.create_group("uniform")
            g["n_clients"] = 1
            pd = g.create_group("partition_data")
            pd.create_group("0")["train"] = [0, 1, 2]
            pd["0"]["test"] = [3]
        fed, n = load_fednlp_text_classification(str(d), batch_size=2)
        assert n == 3                       # widened past declared 2
        assert int(np.asarray(fed.test["y"]).max()) == 2  # c -> id 2

    def test_dispatch_through_data_loader(self, tmp_path):
        from fedml_tpu import data as data_mod
        from fedml_tpu.arguments import Arguments
        d = tmp_path / "fednlp_tiny"
        d.mkdir()
        self._write_fixture(d)
        args = Arguments(dataset="fednlp_tiny", model="lr",
                         client_num_in_total=2, client_num_per_round=2,
                         batch_size=2, data_cache_dir=str(tmp_path))
        fed, output_dim = data_mod.load(args)
        assert output_dim == 6
        assert fed.provenance == "real"
