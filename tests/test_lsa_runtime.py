"""LightSecAgg WAN runtime: one-shot aggregate-mask reconstruction
(reference cross_silo/lightsecagg/lsa_* over core/mpc/lightsecagg math)."""

import threading

import numpy as np
import pytest

pytest.importorskip(
    "cryptography",
    reason="core/mpc/channels.py needs the cryptography package (not"
           " bundled in every runtime image)")

from fedml_tpu import data as data_mod
from fedml_tpu import model as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_silo.horizontal.runner import run_cross_silo_inproc
from fedml_tpu.cross_silo.lightsecagg import (LSAClientManager,
                                              run_lsa_inproc)

pytestmark = __import__('pytest').mark.slow


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=4, client_num_per_round=4,
                comm_round=3, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=1, random_seed=11,
                training_type="cross_silo", federated_optimizer="LSA")
    base.update(kw)
    return Arguments(**base)


def test_lsa_matches_plain_fedavg():
    """The LSA session must produce the same model as plain cross-silo
    FedAvg on identical data/seeds (masks cancel; fixed-point error only)."""
    args = make_args()
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    r_lsa = run_lsa_inproc(args, fed, bundle)
    assert r_lsa is not None and "error" not in r_lsa
    assert len(r_lsa["history"]) == 3
    assert r_lsa["final_test_acc"] > 0.6

    args2 = make_args(federated_optimizer="FedAvg")
    fed2, _ = data_mod.load(args2)
    bundle2 = model_mod.create(args2, output_dim)
    r_plain = run_cross_silo_inproc(args2, fed2, bundle2)

    import jax
    lv = np.concatenate([np.asarray(l).ravel()
                         for l in jax.tree_util.tree_leaves(r_lsa["params"])])
    pv = np.concatenate([np.asarray(l).ravel()
                         for l in jax.tree_util.tree_leaves(
                             r_plain["params"])])
    np.testing.assert_allclose(lv, pv, atol=5e-3)


def test_lsa_survives_dropout():
    """One client drops before uploading; the one-shot decode still
    reconstructs the surviving aggregate (threshold = n-1)."""

    class DroppingClient(LSAClientManager):
        def on_train(self, msg):
            self.finish()  # dies before training/uploading

    args = make_args(comm_round=2, round_timeout_s=15.0)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)

    def factory(rank, a, trainer):
        cls = DroppingClient if rank == 4 else LSAClientManager
        return cls(a, trainer, rank=rank, size=5, backend="INPROC")

    result = run_lsa_inproc(args, fed, bundle, client_factory=factory)
    assert result is not None and "error" not in result, result
    assert len(result["history"]) == 2
    assert all(h["survivors"] == 3 for h in result["history"])
    assert result["final_test_acc"] > 0.5
