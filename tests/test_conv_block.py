"""Parity tests for the fused conv->GroupNorm->residual->ReLU Pallas block
(``core/kernels/conv_block``, ISSUE 16 tentpole).

Tier-1 runs everything here through ``interpret=True`` on CPU (the
``pallas`` marker); the real-TPU compile/execute variant is slow-gated at
the bottom. The XLA :func:`reference_block` is the numerical golden — it
is itself pinned bit-identical to the unfused flax ``BasicBlock``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core.kernels.conv_block import (DEFAULT_BLOCK_N, GN_EPS,
                                               fused_block, reference_block)
from fedml_tpu.model.cv.resnet import BasicBlock, create_resnet

pytestmark = pytest.mark.pallas


def _make_params(rng, cin, cout, proj, dtype=jnp.float32):
    ks = jax.random.split(rng, 8)
    p = {"w1": (jax.random.normal(ks[0], (3, 3, cin, cout)) * 0.2),
         "g1_scale": 1.0 + 0.1 * jax.random.normal(ks[1], (cout,)),
         "g1_bias": 0.1 * jax.random.normal(ks[2], (cout,)),
         "w2": jax.random.normal(ks[3], (3, 3, cout, cout)) * 0.2,
         "g2_scale": 1.0 + 0.1 * jax.random.normal(ks[4], (cout,)),
         "g2_bias": 0.1 * jax.random.normal(ks[5], (cout,))}
    if proj:
        p["wp"] = jax.random.normal(ks[6], (1, 1, cin, cout)) * 0.2
        p["gp_scale"] = 1.0 + 0.1 * jax.random.normal(ks[7], (cout,))
        p["gp_bias"] = jnp.zeros((cout,))
    return jax.tree_util.tree_map(lambda a: a.astype(dtype), p)


def _flax_to_dict(variables):
    v = variables["params"]
    p = {"w1": v["Conv_0"]["kernel"],
         "g1_scale": v["GroupNorm_0"]["scale"],
         "g1_bias": v["GroupNorm_0"]["bias"],
         "w2": v["Conv_1"]["kernel"],
         "g2_scale": v["GroupNorm_1"]["scale"],
         "g2_bias": v["GroupNorm_1"]["bias"]}
    if "Conv_2" in v:
        p["wp"] = v["Conv_2"]["kernel"]
        p["gp_scale"] = v["GroupNorm_2"]["scale"]
        p["gp_bias"] = v["GroupNorm_2"]["bias"]
    return p


@pytest.mark.parametrize("width", [16, 32, 64])
def test_parity_across_channel_widths(width):
    """Kernel vs XLA reference at each narrow-stage width the flagship
    model ships (identity residual, stride 1)."""
    p = _make_params(jax.random.PRNGKey(width), width, width, proj=False)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, width))
    ref = reference_block(x, p, strides=1, groups=8)
    fus = fused_block(x, p, strides=1, groups=8)
    np.testing.assert_allclose(np.asarray(fus), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("h,w,strides", [(7, 9, 1), (7, 7, 2), (9, 8, 2)])
def test_odd_spatial_dims(h, w, strides):
    """Odd extents exercise the pad-then-subsample path (stride-2 samples
    EVEN positions for odd extents, ODD for even — parity-dependent)."""
    proj = strides != 1
    p = _make_params(jax.random.PRNGKey(7), 16, 32 if proj else 16, proj)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, h, w, 16))
    ref = reference_block(x, p, strides=strides, groups=8)
    fus = fused_block(x, p, strides=strides, groups=8)
    assert fus.shape == ref.shape == (2, -(-h // strides),
                                      -(-w // strides),
                                      32 if proj else 16)
    np.testing.assert_allclose(np.asarray(fus), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_projection_residual_branch():
    """Strided stage transition: 1x1-projection + GN residual branch."""
    p = _make_params(jax.random.PRNGKey(3), 16, 32, proj=True)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 8, 8, 16))
    ref = reference_block(x, p, strides=2, groups=8)
    fus = fused_block(x, p, strides=2, groups=8)
    np.testing.assert_allclose(np.asarray(fus), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_channel_change_without_stride():
    """cin != cout at stride 1 also takes the projection branch."""
    p = _make_params(jax.random.PRNGKey(5), 16, 32, proj=True)
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 8, 8, 16))
    np.testing.assert_allclose(
        np.asarray(fused_block(x, p, strides=1, groups=8)),
        np.asarray(reference_block(x, p, strides=1, groups=8)),
        rtol=1e-5, atol=1e-5)


def test_batch_grid_padding():
    """A batch that is not a multiple of the block size pads the grid and
    slices the pad rows back off (and the zero pad rows must not NaN the
    GroupNorm: var 0 -> rsqrt(eps) stays finite)."""
    p = _make_params(jax.random.PRNGKey(8), 16, 16, proj=False)
    n = DEFAULT_BLOCK_N + 3
    x = jax.random.normal(jax.random.PRNGKey(9), (n, 8, 8, 16))
    fus = fused_block(x, p)
    assert fus.shape[0] == n
    assert np.isfinite(np.asarray(fus)).all()
    np.testing.assert_allclose(np.asarray(fus),
                               np.asarray(reference_block(x, p)),
                               rtol=1e-5, atol=1e-5)


def test_bf16_parity():
    p = _make_params(jax.random.PRNGKey(10), 16, 16, proj=False,
                     dtype=jnp.bfloat16)
    x = jax.random.normal(jax.random.PRNGKey(11), (4, 8, 8, 16),
                          dtype=jnp.bfloat16)
    ref = reference_block(x, p)
    fus = fused_block(x, p)
    assert fus.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(fus, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=0.06, atol=0.06)


def test_grad_through_kernel():
    """``jax.grad`` through the fused block (custom_vjp with
    reference-recompute backward) matches the reference path's gradients
    for both the input and every parameter leaf."""
    p = _make_params(jax.random.PRNGKey(12), 16, 32, proj=True)
    x = jax.random.normal(jax.random.PRNGKey(13), (1, 8, 8, 16))

    def loss(fn):
        return lambda x_, p_: jnp.sum(
            fn(x_, p_, strides=2, groups=8) ** 2)

    gx_f, gp_f = jax.grad(loss(fused_block), argnums=(0, 1))(x, p)
    gx_r, gp_r = jax.grad(loss(reference_block), argnums=(0, 1))(x, p)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_r),
                               rtol=1e-4, atol=1e-4)
    for k in gp_r:
        np.testing.assert_allclose(np.asarray(gp_f[k]),
                                   np.asarray(gp_r[k]),
                                   rtol=1e-4, atol=1e-4, err_msg=k)


def test_grad_under_jit_and_scan():
    """The engine wraps the model in jit(scan(...)) — the custom_vjp must
    survive that composition."""
    p = _make_params(jax.random.PRNGKey(14), 16, 16, proj=False)
    x = jax.random.normal(jax.random.PRNGKey(15), (3, 2, 8, 8, 16))

    @jax.jit
    def total(p_):
        def body(c, xb):
            g = jax.grad(
                lambda pp: jnp.sum(fused_block(xb, pp) ** 2))(p_)
            return c + g["w1"].sum(), None
        out, _ = jax.lax.scan(body, jnp.float32(0), x)
        return out

    assert np.isfinite(float(total(p)))


def test_reference_block_matches_flax_bitwise():
    """The XLA reference path is the golden: on params extracted from the
    unfused flax module it must reproduce flax bit-for-bit (same conv
    primitive, same one-pass f32 GroupNorm formula, same op order)."""
    for filters, strides, cin in ((16, 1, 16), (32, 2, 16)):
        m = BasicBlock(filters, strides)
        x = jax.random.normal(jax.random.PRNGKey(16), (3, 8, 8, cin))
        variables = m.init(jax.random.PRNGKey(17), x)
        out_flax = m.apply(variables, x)
        out_ref = reference_block(x, _flax_to_dict(variables),
                                  strides=strides,
                                  groups=min(8, filters))
        assert np.array_equal(np.asarray(out_flax), np.asarray(out_ref))


def test_fused_module_init_tree_bit_identical():
    """``fused`` modes declare params through explicitly-named child
    scopes (Conv_0/GroupNorm_0/...), so the init tree — names AND values
    — is bit-identical to the unfused module's: checkpoints and the
    engine's flat-vector machinery are mode-agnostic."""
    x = jnp.zeros((1, 8, 8, 16))
    base = BasicBlock(32, strides=2).init(jax.random.PRNGKey(18), x)
    for mode in ("pallas", "reference"):
        fused = BasicBlock(32, strides=2, fused=mode).init(
            jax.random.PRNGKey(18), x)
        flat_b = jax.tree_util.tree_leaves_with_path(base)
        flat_f = jax.tree_util.tree_leaves_with_path(fused)
        assert [p for p, _ in flat_b] == [p for p, _ in flat_f]
        for (pb, lb), (_, lf) in zip(flat_b, flat_f):
            assert np.array_equal(np.asarray(lb), np.asarray(lf)), pb


@pytest.mark.parametrize(
    "mode",
    ["reference",
     # the pallas whole-model pass re-runs the interpret-mode kernel 9
     # blocks deep (~12 s on a 1-core CPU) and its numerics are already
     # tier-1-covered per block; keep whole-model wiring in tier-1 via
     # the reference mode and gate the pallas repeat behind slow
     pytest.param("pallas", marks=pytest.mark.slow)])
def test_resnet20_model_parity(mode):
    """Whole-model parity: resnet20 with every narrow block fused vs the
    unfused flax path, same init tree, same logits within f32 tolerance."""
    base = create_resnet("resnet20", 10)
    fused = create_resnet("resnet20", 10, fused=mode)
    x = jax.random.normal(jax.random.PRNGKey(19), (1, 8, 8, 3))
    vb = base.init(jax.random.PRNGKey(20), x, train=False)
    vf = fused.init(jax.random.PRNGKey(20), x, train=False)
    for (pb, lb), (_, lf) in zip(
            jax.tree_util.tree_leaves_with_path(vb),
            jax.tree_util.tree_leaves_with_path(vf)):
        assert np.array_equal(np.asarray(lb), np.asarray(lf)), pb
    out_b = base.apply(vb, x, train=False)
    out_f = fused.apply(vf, x, train=False)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_b),
                               rtol=1e-4, atol=1e-4)


def test_model_hub_knob_threading():
    """``fused_conv_block`` reaches the resnet factory through
    ``model.create`` and an off/absent knob keeps the original module."""
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.model import create

    def bundle(**kw):
        return create(Arguments(dataset="cifar10", model="resnet20",
                                allow_synthetic=True, **kw), 10)

    assert bundle().module.fused == ""
    assert bundle(fused_conv_block=False).module.fused == ""
    assert bundle(fused_conv_block=True).module.fused == "pallas"
    assert bundle(fused_conv_block="reference").module.fused == "reference"
    with pytest.raises(ValueError):
        bundle(fused_conv_block="mystery")


def test_wide_blocks_stay_unfused():
    """Blocks wider than MAX_FUSED_CHANNELS (ResNet-18's 128-512 channel
    stages) keep the flax path even with the knob on — the narrow-stage
    kernel must not be asked to hold ImageNet activations in VMEM. Since
    the width gate routes to the IDENTICAL flax code, the output must be
    bit-equal, not merely close."""
    from fedml_tpu.core.kernels.conv_block import MAX_FUSED_CHANNELS

    wide = MAX_FUSED_CHANNELS * 2
    x = jax.random.normal(jax.random.PRNGKey(21), (2, 4, 4, wide))
    base = BasicBlock(wide, strides=1)
    m = BasicBlock(wide, strides=1, fused="pallas")
    v = base.init(jax.random.PRNGKey(22), x)
    assert np.array_equal(np.asarray(m.apply(v, x)),
                          np.asarray(base.apply(v, x)))


@pytest.mark.slow
def test_real_tpu_compile_and_parity():
    """Mosaic-compiled (non-interpret) variant — only meaningful on a
    real TPU backend."""
    if jax.default_backend() != "tpu":
        pytest.skip("real-TPU pallas variant (interpret path is tier-1)")
    p = _make_params(jax.random.PRNGKey(23), 16, 16, proj=False)
    x = jax.random.normal(jax.random.PRNGKey(24), (8, 32, 32, 16))
    np.testing.assert_allclose(
        np.asarray(fused_block(x, p), np.float32),
        np.asarray(reference_block(x, p), np.float32),
        rtol=1e-4, atol=1e-4)
