"""Wire-efficient cross-silo updates (utils/compression.py QSGD + error
feedback, ISSUE 1): quantizer round-trip properties, residual carry across
rounds, bytes-on-wire accounting at the encode seam, the byte-identical
guarantee when compression is off, and (slow) a full in-proc FL session
with compression on matching the dense session's accuracy ballpark."""

import jax
import msgpack
import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core.distributed.communication.message import (WIRE_DTYPE_BF16,
                                                              WIRE_STATS,
                                                              Message,
                                                              _pack_np,
                                                              bf16_wire_to_tree,
                                                              tree_to_wire,
                                                              tree_to_wire_bf16)
from fedml_tpu.cross_silo.message_define import MyMessage
from fedml_tpu.utils.compression import (CommCompressionSpec, decompress_vec,
                                         ef_compress_vec,
                                         is_compressed_payload,
                                         qsgd_dequantize, qsgd_quantize,
                                         spec_from_args)


class TestQSGD:
    def test_roundtrip_dtype_and_shape(self):
        vec = np.linspace(-2.0, 3.0, 64).astype(np.float32)
        q, scale = qsgd_quantize(vec, 127, jax.random.PRNGKey(0))
        assert q.dtype == np.int8 and q.shape == vec.shape
        deq = np.asarray(qsgd_dequantize(q, scale, 127))
        # quantization error bounded by one level
        assert np.max(np.abs(deq - vec)) <= float(scale) / 127 + 1e-6

    def test_unbiased(self):
        """E[dequantize(quantize(v))] = v — the stochastic rounding must
        not drift the aggregate."""
        vec = np.linspace(-1.0, 1.0, 32).astype(np.float32)
        acc = np.zeros_like(vec)
        trials = 400
        for i in range(trials):
            q, s = qsgd_quantize(vec, 7, jax.random.PRNGKey(i))
            acc += np.asarray(qsgd_dequantize(q, s, 7))
        np.testing.assert_allclose(acc / trials, vec, atol=0.03)

    def test_zero_vector_safe(self):
        q, s = qsgd_quantize(np.zeros(8, np.float32), 127,
                             jax.random.PRNGKey(0))
        assert float(s) == 0.0
        assert np.all(np.asarray(qsgd_dequantize(q, s, 127)) == 0.0)


class TestEFCompress:
    def spec(self, method="topk", ratio=0.25):
        return CommCompressionSpec(method=method, ratio=ratio)

    def test_blob_shapes_and_decompress(self):
        d = 100
        vec = np.random.RandomState(0).randn(d).astype(np.float32)
        blob, res = ef_compress_vec(vec, None, self.spec("topk_qsgd"),
                                    jax.random.PRNGKey(0))
        assert is_compressed_payload(blob)
        assert blob["v"].dtype == np.int8          # quantized values
        assert blob["i"].dtype == np.uint16        # small-d index dtype
        assert blob["i"].shape == (25,)            # ratio 0.25 of 100
        out = decompress_vec(blob)
        assert out.shape == (d,) and out.dtype == np.float32
        # only k coordinates are nonzero, and they are the top-k ones
        assert np.count_nonzero(out) <= 25
        assert res.shape == (d,)

    def test_pure_qsgd_has_no_index_list(self):
        vec = np.random.RandomState(1).randn(50).astype(np.float32)
        blob, _ = ef_compress_vec(vec, None, self.spec("qsgd"),
                                  jax.random.PRNGKey(0))
        assert "i" not in blob and blob["v"].shape == (50,)
        out = decompress_vec(blob)
        assert out.shape == (50,)
        assert np.max(np.abs(out - vec)) <= float(blob["s"]) / 127 + 1e-6

    def test_error_feedback_carries_dropped_mass(self):
        """With a constant gradient, EF top-k must transmit the small
        coordinates eventually: cumulative reconstruction stays within a
        bounded distance of the cumulative gradient, while the no-feedback
        compressor's error grows linearly in T."""
        rs = np.random.RandomState(2)
        g = rs.randn(40).astype(np.float32)
        spec = self.spec("topk", ratio=0.1)   # k = 4 of 40
        T = 30
        res, acc = None, np.zeros_like(g)
        acc_nofb = np.zeros_like(g)
        for t in range(T):
            blob, res = ef_compress_vec(g, res, spec, jax.random.PRNGKey(t))
            acc += decompress_vec(blob)
            blob_nofb, _ = ef_compress_vec(g, np.zeros_like(g), spec,
                                           jax.random.PRNGKey(t))
            acc_nofb += decompress_vec(blob_nofb)
        err_ef = np.linalg.norm(acc - T * g)
        err_nofb = np.linalg.norm(acc_nofb - T * g)
        # EF error equals the current residual, whose steady state for
        # top-k is bounded by ~(d/2k)=5x ||g||; the no-feedback error is
        # T * (dropped mass), which keeps growing with T
        assert err_ef < 6.0 * np.linalg.norm(g)
        assert err_nofb > 5.0 * err_ef

    def test_randk_under_ef_converges_on_constant_gradient(self):
        """The EF rand-k core is contractive (no d/k rescale): the
        residual must stay bounded instead of exploding."""
        g = np.ones(30, np.float32)
        spec = self.spec("randk", ratio=0.2)
        res = None
        for t in range(50):
            _, res = ef_compress_vec(g, res, spec, jax.random.PRNGKey(t))
        assert np.linalg.norm(res) < 10.0 * np.linalg.norm(g)


class TestSpec:
    def test_defaults_off(self):
        assert spec_from_args(Arguments()) is None
        assert spec_from_args(Arguments(comm_compression="none")) is None

    def test_parse_and_validate(self):
        spec = spec_from_args(Arguments(comm_compression="topk_qsgd",
                                        comm_compression_ratio=0.05,
                                        comm_compression_broadcast="bf16"))
        assert spec.method == "topk_qsgd" and spec.quantized
        assert spec.ratio == 0.05 and spec.broadcast == "bf16"
        with pytest.raises(ValueError, match="unknown comm_compression"):
            CommCompressionSpec(method="gzip")
        with pytest.raises(ValueError, match="ratio"):
            CommCompressionSpec(method="topk", ratio=1.5)
        with pytest.raises(ValueError, match="levels"):
            CommCompressionSpec(method="qsgd", levels=500)
        with pytest.raises(ValueError, match="broadcast"):
            CommCompressionSpec(method="topk", broadcast="fp8")

    def test_broadcast_only_spec(self):
        """comm_compression_broadcast=bf16 alone must yield a working spec
        (half-width downlink, dense uplink) — not be silently ignored; a
        compress broadcast without a compressor is a config error."""
        spec = spec_from_args(Arguments(comm_compression_broadcast="bf16"))
        assert spec is not None and spec.method is None
        assert spec.broadcast == "bf16" and not spec.quantized
        with pytest.raises(ValueError, match="needs a compressor"):
            spec_from_args(Arguments(comm_compression_broadcast="compress"))


class TestWireFormat:
    def params(self):
        return {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                "b": np.ones(4, np.float32)}

    def test_compression_off_encode_is_byte_identical(self):
        """Regression for the opt-in guarantee: with compression off the
        encode seam must produce exactly the plain msgpack encoding of the
        params dict — no extra keys, marks, or re-ordering."""
        msg = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, 1, 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                       tree_to_wire(self.params()))
        msg.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 32.0)
        blob = msg.encode()
        assert blob == msgpack.packb(msg.msg_params, default=_pack_np,
                                     use_bin_type=True)
        back = Message.decode(blob)
        got = back.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        for k, v in tree_to_wire(self.params()).items():
            np.testing.assert_array_equal(got[k], v)

    def test_dense_client_payload_unchanged_when_off(self):
        """The client FSM with default args must emit the dense payload
        under the same key with the same values as before this layer."""
        from fedml_tpu.cross_silo.client.fedml_client_master_manager import (
            ClientMasterManager)

        class StubTrainer:
            params_template = {"w": np.zeros((3, 4), np.float32)}

            def train(self, params, client_idx, round_idx):
                new = {"w": np.asarray(params["w"]) + 1.0}
                return new, 7.0, {"train_loss": 0.5}

        class StubComm:
            def add_observer(self, o): ...
            def send_message(self, m): ...

        mgr = ClientMasterManager.__new__(ClientMasterManager)
        mgr.args = Arguments()
        mgr.rank, mgr.server_rank, mgr.round_idx = 1, 0, 0
        mgr.trainer = StubTrainer()
        mgr.cc_spec = spec_from_args(mgr.args)
        mgr._cc_residual = mgr._global_vec = None
        sent = []
        mgr.send_message = sent.append
        mgr.com_manager = StubComm()

        inc = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, 1)
        inc.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                       tree_to_wire(StubTrainer.params_template))
        inc.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0)
        mgr._train_and_report(inc)
        assert len(sent) == 1
        out = sent[0]
        assert out.get(MyMessage.MSG_ARG_KEY_MODEL_UPDATE) is None
        np.testing.assert_array_equal(
            out.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)["w"],
            np.ones((3, 4), np.float32))
        assert out.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES) == 7.0

    def test_bf16_only_broadcast_keeps_dense_uplink(self):
        """A broadcast-only spec must leave the client's uplink dense —
        the compression machinery (delta, residual) only engages when a
        method is configured."""
        from fedml_tpu.cross_silo.client.fedml_client_master_manager import (
            ClientMasterManager)

        class StubTrainer:
            params_template = {"w": np.zeros((3, 4), np.float32)}

            def train(self, params, client_idx, round_idx):
                return {"w": np.asarray(params["w"]) + 1.0}, 7.0, {}

        mgr = ClientMasterManager.__new__(ClientMasterManager)
        mgr.args = Arguments(comm_compression_broadcast="bf16")
        mgr.rank, mgr.server_rank, mgr.round_idx = 1, 0, 0
        mgr.trainer = StubTrainer()
        mgr.cc_spec = spec_from_args(mgr.args)
        mgr._cc_residual = mgr._global_vec = None
        sent = []
        mgr.send_message = sent.append

        inc = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, 0, 1)
        inc.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                       tree_to_wire_bf16(StubTrainer.params_template))
        inc.add_params(MyMessage.MSG_ARG_KEY_WIRE_DTYPE, WIRE_DTYPE_BF16)
        inc.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0)
        mgr._train_and_report(inc)
        assert len(sent) == 1
        out = sent[0]
        assert out.get(MyMessage.MSG_ARG_KEY_MODEL_UPDATE) is None
        np.testing.assert_array_equal(
            out.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)["w"],
            np.ones((3, 4), np.float32))

    def test_compressed_blob_survives_msgpack(self):
        vec = np.random.RandomState(3).randn(70).astype(np.float32)
        spec = CommCompressionSpec(method="topk_qsgd", ratio=0.2)
        blob, _ = ef_compress_vec(vec, None, spec, jax.random.PRNGKey(0))
        msg = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, 1, 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_UPDATE, blob)
        back = Message.decode(msg.encode())
        got = back.get(MyMessage.MSG_ARG_KEY_MODEL_UPDATE)
        assert is_compressed_payload(got)
        np.testing.assert_array_equal(decompress_vec(got),
                                      decompress_vec(blob))

    def test_bf16_wire_roundtrip(self):
        tree = {"w": np.linspace(-3, 3, 24).astype(np.float32).reshape(4, 6)}
        wire = tree_to_wire_bf16(tree)
        assert wire["w"].dtype == np.uint16     # codec-neutral bit view
        back = bf16_wire_to_tree(wire, tree)
        assert back["w"].dtype == np.float32
        # bf16 keeps ~8 mantissa bits: 2^-7 relative error
        np.testing.assert_allclose(back["w"], tree["w"], rtol=2 ** -7)

    def test_wire_stats_ledger(self):
        WIRE_STATS.reset()
        msg = Message("t", 0, 1)
        n = len(msg.encode())
        msg.encode()
        snap = WIRE_STATS.snapshot()
        assert snap["total_messages"] == 2
        assert snap["total_bytes"] == 2 * n
        assert snap["by_type"]["t"] == {"bytes": 2 * n, "messages": 2}
        WIRE_STATS.reset()
        assert WIRE_STATS.total_bytes == 0


class TestServerBaseTracking:
    def _manager(self, spec):
        import threading

        from fedml_tpu.cross_silo.server.fedml_server_manager import (
            FedMLServerManager)
        mgr = FedMLServerManager.__new__(FedMLServerManager)
        mgr.cc_spec = spec
        mgr._bcast_prev_vec = None
        mgr._bcast_residual = None
        mgr._cc_rng = jax.random.PRNGKey(0)
        mgr._round_lock = threading.Lock()
        mgr._round_timer = None
        mgr.round_timeout_s = 0.0
        mgr.round_idx = 3
        return mgr

    def test_bf16_broadcast_tracks_client_reconstruction(self):
        """With a bf16 broadcast, compressed deltas refer to the bf16
        ROUNDING the clients hold — _sync_payload must track exactly that
        vector as the base, not the exact f32 global."""
        from fedml_tpu.core.collectives import tree_flatten_to_vector
        mgr = self._manager(CommCompressionSpec(
            method="topk", ratio=0.5, broadcast="bf16"))

        class Agg:
            global_params = {"w": np.linspace(-1.0, 1.0, 9).astype(
                np.float32).reshape(3, 3)}
        mgr.aggregator = Agg()
        payload = dict(mgr._sync_payload())
        assert payload[MyMessage.MSG_ARG_KEY_WIRE_DTYPE] == WIRE_DTYPE_BF16
        widened = bf16_wire_to_tree(
            payload[MyMessage.MSG_ARG_KEY_MODEL_PARAMS], Agg.global_params)
        np.testing.assert_array_equal(
            mgr._bcast_prev_vec,
            np.asarray(tree_flatten_to_vector(widened), np.float32))

    def test_full_broadcast_refreshes_base_for_compressed_uplinks(self):
        """With broadcast='full' and compressed uplinks, the handler must
        hand the aggregator the base captured under _round_lock — never
        defer to the aggregator's live global, which a round-timeout
        aggregation can advance between the stale check and the add."""
        spec = CommCompressionSpec(method="topk", ratio=0.5,
                                   broadcast="full")
        mgr = self._manager(spec)
        bases = []

        class Agg:
            global_params = {"w": np.arange(4, dtype=np.float32)}

            def add_local_trained_delta(self, index, delta, n,
                                        base_vec=None):
                # the add must share the stale check's lock acquisition —
                # otherwise a round-timeout aggregation can slip between
                # them and this model lands in the NEXT round's pool
                assert mgr._round_lock.locked()
                bases.append(base_vec)

            def check_whether_all_receive(self):
                return False
        mgr.aggregator = Agg()
        payload = dict(mgr._sync_payload())
        assert MyMessage.MSG_ARG_KEY_MODEL_UPDATE not in payload
        np.testing.assert_array_equal(mgr._bcast_prev_vec,
                                      np.arange(4, dtype=np.float32))
        blob, _ = ef_compress_vec(np.ones(4, np.float32), None, spec,
                                  jax.random.PRNGKey(0))
        msg = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, 1, 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_UPDATE, blob)
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, 3)
        msg.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 1.0)
        mgr.handle_message_receive_model_from_client(msg)
        assert len(bases) == 1 and bases[0] is mgr._bcast_prev_vec

    def test_bf16_only_broadcast_skips_base_tracking(self):
        """A broadcast-only spec (method None) gets no client deltas:
        the payload is bf16-tagged but no base is tracked."""
        mgr = self._manager(CommCompressionSpec(method=None,
                                                broadcast="bf16"))

        class Agg:
            global_params = {"w": np.ones((2, 2), np.float32)}
        mgr.aggregator = Agg()
        payload = dict(mgr._sync_payload())
        assert payload[MyMessage.MSG_ARG_KEY_WIRE_DTYPE] == WIRE_DTYPE_BF16
        assert mgr._bcast_prev_vec is None

    def test_stale_compressed_update_dropped(self):
        """A compressed straggler from a timed-out round must be dropped,
        not reconstructed against the NEXT round's base."""
        spec = CommCompressionSpec(method="topk", ratio=0.5)
        mgr = self._manager(spec)
        calls = []

        class Agg:
            def add_local_trained_delta(self, *a, **k):
                calls.append(("delta", a))

            def check_whether_all_receive(self):
                return False
        mgr.aggregator = Agg()
        blob, _ = ef_compress_vec(np.ones(4, np.float32), None, spec,
                                  jax.random.PRNGKey(0))

        def upload(round_idx):
            msg = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, 1, 0)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_UPDATE, blob)
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_IDX, round_idx)
            msg.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 1.0)
            mgr.handle_message_receive_model_from_client(msg)

        upload(2)          # stale: server already advanced to round 3
        assert calls == []
        upload(3)          # current round: accepted
        assert len(calls) == 1


@pytest.mark.slow
class TestCompressedSession:
    def test_inproc_session_with_compression_matches_dense_ballpark(self):
        from fedml_tpu import data as data_mod
        from fedml_tpu import model as model_mod
        from fedml_tpu.cross_silo.horizontal.runner import (
            run_cross_silo_inproc)
        args = Arguments(dataset="synthetic_mnist", model="lr",
                         client_num_in_total=4, client_num_per_round=4,
                         comm_round=4, epochs=1, batch_size=32,
                         learning_rate=0.1, frequency_of_the_test=1,
                         random_seed=9, training_type="cross_silo",
                         comm_compression="topk_qsgd",
                         comm_compression_ratio=0.1,
                         comm_compression_broadcast="compress")
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        WIRE_STATS.reset()
        result = run_cross_silo_inproc(args, fed, bundle)
        by_type = WIRE_STATS.snapshot()["by_type"]
        assert result is not None
        # same bar the dense session test uses
        assert result["final_test_acc"] > 0.6, result["history"]
        # per-round ledger surfaced through the server history
        assert all(h.get("wire_bytes", 0) > 0 for h in result["history"])
        # model-bearing uploads shrank by at least the sparsity factor/2
        c2s = by_type[str(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER)]
        dense_nbytes = 4 * sum(
            int(np.prod(v.shape)) for v in tree_to_wire(
                bundle.init(jax.random.PRNGKey(0),
                            fed.train.x[0, 0])).values())
        assert c2s["bytes"] / c2s["messages"] < dense_nbytes / 5
