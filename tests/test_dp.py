"""Differential privacy: mechanism calibration, RDP accountant math, and
LDP/CDP end-to-end with SP/TPU parity."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.dp import (FedMLDifferentialPrivacy, RDPAccountant,
                               clip_by_global_norm, gaussian_sigma)
from fedml_tpu.core.dp.mechanisms import add_gaussian_noise


class TestMechanisms:
    def test_gaussian_sigma_calibration(self):
        # eps=1, delta=1e-5, s=1 -> sigma = sqrt(2 ln(1.25e5)) ~ 4.84
        s = gaussian_sigma(1.0, 1e-5, 1.0)
        assert abs(s - math.sqrt(2 * math.log(1.25e5))) < 1e-9

    def test_clip_by_global_norm(self):
        tree = {"a": jnp.ones((10,)) * 3.0, "b": jnp.ones((5,)) * 4.0}
        clipped = clip_by_global_norm(tree, 1.0)
        total = sum(float(jnp.sum(jnp.square(l)))
                    for l in jax.tree_util.tree_leaves(clipped))
        assert abs(math.sqrt(total) - 1.0) < 1e-5
        # under the bound -> unchanged
        small = clip_by_global_norm(tree, 1e9)
        np.testing.assert_allclose(np.asarray(small["a"]), 3.0)

    def test_noise_statistics(self):
        tree = {"w": jnp.zeros((20000,))}
        noised = add_gaussian_noise(tree, jax.random.PRNGKey(0), 2.0)
        std = float(jnp.std(noised["w"]))
        assert abs(std - 2.0) < 0.1


class TestAccountant:
    def test_more_steps_more_epsilon(self):
        a1, a2 = RDPAccountant(), RDPAccountant()
        a1.step(1.0, 0.1, num_steps=10)
        a2.step(1.0, 0.1, num_steps=100)
        assert a2.get_epsilon(1e-5) > a1.get_epsilon(1e-5) > 0

    def test_more_noise_less_epsilon(self):
        a1, a2 = RDPAccountant(), RDPAccountant()
        a1.step(0.8, 0.1, num_steps=50)
        a2.step(4.0, 0.1, num_steps=50)
        assert a2.get_epsilon(1e-5) < a1.get_epsilon(1e-5)

    def test_subsampling_amplifies(self):
        full, sub = RDPAccountant(), RDPAccountant()
        full.step(1.0, 1.0, num_steps=10)
        sub.step(1.0, 0.01, num_steps=10)
        assert sub.get_epsilon(1e-5) < full.get_epsilon(1e-5)

    def test_known_regime(self):
        # sigma=1, q=1, 1 step, delta=1e-5: eps ~ 4-6 by the standard
        # RDP->DP conversion
        a = RDPAccountant()
        a.step(1.0, 1.0, num_steps=1)
        eps = a.get_epsilon(1e-5)
        assert 3.0 < eps < 7.0, eps


def sim_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=8, client_num_per_round=8,
                comm_round=3, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=3, random_seed=5)
    base.update(kw)
    return Arguments(**base)


class TestEndToEnd:
    def test_ldp_sp_tpu_parity(self):
        kw = dict(enable_dp=True, dp_type="local_dp", dp_epsilon=50.0,
                  dp_delta=1e-5, dp_clip_norm=5.0)
        r_sp = fedml_tpu.run_simulation(backend="sp", args=sim_args(**kw))
        r_tpu = fedml_tpu.run_simulation(backend="tpu", args=sim_args(**kw))
        for a, b in zip(jax.tree_util.tree_leaves(r_sp["params"]),
                        jax.tree_util.tree_leaves(r_tpu["params"])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5)
        assert r_tpu["dp_epsilon_spent"] > 0

    def test_cdp_still_learns_with_mild_noise(self):
        r = fedml_tpu.run_simulation(backend="tpu", args=sim_args(
            enable_dp=True, dp_type="central_dp", dp_epsilon=100.0,
            dp_delta=1e-5, dp_clip_norm=10.0, comm_round=8))
        assert r["final_test_acc"] > 0.5
        assert "dp_epsilon_spent" in r

    def test_strong_ldp_noise_hurts(self):
        clean = fedml_tpu.run_simulation(backend="tpu", args=sim_args())
        noisy = fedml_tpu.run_simulation(backend="tpu", args=sim_args(
            enable_dp=True, dp_type="local_dp", dp_epsilon=0.1,
            dp_delta=1e-5, dp_clip_norm=0.5))
        assert noisy["final_test_acc"] < clean["final_test_acc"] + 0.02
