"""Single-dispatch robust rounds + HBM buffer donation (ISSUE 2).

The fused robust program (train -> on-device attack -> sharded defense ->
central-DP noise -> server transform, one jitted SPMD call) must match the
host-orchestrated path client-for-client — same defense verdicts, so same
params — with and without a model attack and CDP. Buffer donation must be
safe across rounds and checkpoint restore. And the fused programs must
compile exactly once per run (canonical schedule width), which the
xla_compile_counter fixture pins so shape-instability regressions fail
loudly instead of silently recompiling every round.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments


def sim_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=8, client_num_per_round=8,
                comm_round=3, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=3, random_seed=3)
    base.update(kw)
    return Arguments(**base)


def build_sim(args):
    from fedml_tpu import data as data_mod, model as model_mod
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    return TPUSimulator(args, fed, bundle, create_optimizer(args, spec),
                        spec)


def hyper_for(args):
    from fedml_tpu.core.algframe.types import TrainHyper
    return TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                      epochs=int(args.epochs))


def assert_params_close(a, b, rtol=1e-5, atol=1e-6):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=rtol, atol=atol)


DEFENSE_KW = dict(enable_defense=True, defense_type="multi_krum",
                  krum_param_m=3, byzantine_client_num=2)
# byzantine_client_num rides DEFENSE_KW (the attacker reads the same key)
ATTACK_KW = dict(enable_attack=True, attack_type="byzantine_flip",
                 attack_scale=5.0)


class TestFusedRobustParity:
    """Fused path == host-dispatch path, client-for-client."""

    def _parity(self, **kw):
        r_fused = fedml_tpu.run_simulation(backend="tpu",
                                           args=sim_args(**kw))
        r_host = fedml_tpu.run_simulation(
            backend="tpu", args=sim_args(robust_fused="host", **kw))
        assert_params_close(r_fused["params"], r_host["params"])

    def test_defense_only_parity(self):
        self._parity(**DEFENSE_KW)

    def test_attack_and_defense_parity(self):
        self._parity(**DEFENSE_KW, **ATTACK_KW)

    def test_cdp_parity(self):
        """Central-DP noise rides the SAME key and mechanism on both
        paths, so even the noised params must agree."""
        self._parity(enable_dp=True, dp_type="central_dp", dp_epsilon=8.0,
                     **DEFENSE_KW, **ATTACK_KW)

    def test_stochastic_attack_parity(self):
        """byzantine_random folds the shard index into the attack key on
        both paths — the noise streams must line up shard-for-shard."""
        self._parity(enable_defense=True, defense_type="coordinate_median",
                     enable_attack=True, attack_type="byzantine_random",
                     byzantine_client_num=2, attack_scale=10.0)

    def test_fused_engine_is_selected_and_single_dispatch(self):
        """auto selects the fused program for a sharded-capable defended
        config, and the whole defended round runs without any
        device->host transfer."""
        args = sim_args(**DEFENSE_KW, **ATTACK_KW)
        sim = build_sim(args)
        assert sim.robust_fused
        hyper = hyper_for(args)
        with jax.transfer_guard_device_to_host("disallow"):
            metrics = sim.run_round(0, hyper)
        assert float(metrics["count"]) > 0  # readback OUTSIDE the guard
        assert sim.dispatch_stats["dispatches"] == 1

    def test_fused_multi_round_block_matches_per_round(self):
        """One 4-round dispatch == four single-round dispatches."""
        args = sim_args(**DEFENSE_KW)
        hyper = hyper_for(args)
        sim_block = build_sim(args)
        sim_loop = build_sim(args)
        metrics = sim_block.run_rounds_fused(0, 4, hyper)
        assert len(metrics) == 4
        assert sim_block.dispatch_stats["dispatches"] == 1
        for r in range(4):
            sim_loop.run_round(r, hyper)
        assert_params_close(sim_block.params, sim_loop.params)

    def test_robust_fused_refuses_unfusable_config(self):
        """robust_fused: fused must refuse (not silently degrade) configs
        that cannot fuse — here the sharded path is forced off."""
        args = sim_args(enable_defense=True, defense_type="multi_krum",
                        sharded_defense="false", robust_fused="fused")
        with pytest.raises(ValueError, match="robust_fused"):
            build_sim(args)

    def test_host_only_robust_configs_fall_back(self, caplog):
        """sharded_defense: false keeps the host kernels — auto must fall
        back to the collect path (not crash) and say WHICH knob forced
        the host path, exactly once."""
        args = sim_args(enable_defense=True, defense_type="multi_krum",
                        sharded_defense="false")
        with caplog.at_level(logging.INFO,
                             logger="fedml_tpu.simulation.tpu.engine"):
            sim = build_sim(args)
            assert sim.robust_mode and not sim.robust_fused
            sim.run_round(0, hyper_for(args))
            sim.run_round(1, hyper_for(args))
        host_logs = [r for r in caplog.records
                     if "HOST-dispatch path" in r.getMessage()]
        assert len(host_logs) == 1
        assert "sharded_defense" in host_logs[0].getMessage()


class TestNewFusedDefenses:
    """ISSUE 4: bulyan / RFA / foolsgold (and the other former host-only
    defenses) fuse — the single-dispatch program must match the
    host-dispatch path client-for-client, stateful history included."""

    def _parity(self, **kw):
        r_fused = fedml_tpu.run_simulation(backend="tpu",
                                           args=sim_args(**kw))
        r_host = fedml_tpu.run_simulation(
            backend="tpu", args=sim_args(robust_fused="host", **kw))
        assert_params_close(r_fused["params"], r_host["params"])
        return r_fused, r_host

    @pytest.mark.parametrize("defense", ["bulyan", "rfa", "foolsgold"])
    def test_defense_parity_under_attack(self, defense):
        """Same seeds, same verdicts: fused == host client-for-client for
        the defenses PR 2 left on the host path, with a byzantine-flip
        attack in the loop (the regime these defenses exist for)."""
        self._parity(enable_defense=True, defense_type=defense,
                     byzantine_client_num=2, **ATTACK_KW)

    @pytest.mark.parametrize("defense", ["cclip", "cross_round", "slsgd"])
    def test_stateful_defense_parity(self, defense):
        """Cross-round device state (cclip momentum, cross_round previous
        updates, slsgd prev-global) must evolve identically on both
        paths across a multi-round run."""
        self._parity(enable_defense=True, defense_type=defense,
                     comm_round=4)

    def test_fused_selected_for_all_builtin_defenses(self):
        """Every defense in DEFENSE_TYPES now takes the fused path under
        robust_fused: auto — the host fallback is gone for built-ins."""
        from fedml_tpu.core.security.defense import DEFENSE_TYPES
        for d in DEFENSE_TYPES:
            sim = build_sim(sim_args(enable_defense=True, defense_type=d))
            assert sim.robust_fused, d

    def test_foolsgold_downweights_sybils_on_device(self):
        """Semantics, not just parity: two colluding clients pushing the
        same poisoned direction every round must end up down-weighted
        versus the honest majority (the history accumulates on device)."""
        args = sim_args(enable_defense=True, defense_type="foolsgold",
                        enable_attack=True, attack_type="byzantine_flip",
                        byzantine_client_num=2, attack_scale=5.0)
        sim = build_sim(args)
        assert sim.robust_fused and sim._defense_state is not None
        hyper = hyper_for(args)
        for r in range(3):
            sim.run_round(r, hyper)
        hist = np.asarray(sim._defense_state["history"])
        assert np.abs(hist).sum() > 0  # accumulated, not amnesiac
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree_util.tree_leaves(sim.params))


class TestFoolsgoldCheckpoint:
    """The foolsgold history is engine state now — it must ride
    RoundCheckpointer saves so crash-resume replays identical weights."""

    def test_defense_state_in_ckpt_state(self):
        args = sim_args(enable_defense=True, defense_type="foolsgold")
        sim = build_sim(args)
        st = sim._ckpt_state()
        assert "defense_state" in st and "history" in st["defense_state"]
        sim.run_round(0, hyper_for(args))
        assert np.abs(np.asarray(sim._defense_state["history"])).sum() > 0

    def test_foolsgold_history_checkpoint_roundtrip(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        args = sim_args(enable_defense=True, defense_type="foolsgold",
                        checkpoint_dir=str(tmp_path / "ckpt"),
                        checkpoint_every_rounds=2, comm_round=4)
        fedml_tpu.run_simulation(backend="tpu", args=args)
        args2 = sim_args(enable_defense=True, defense_type="foolsgold",
                         checkpoint_dir=str(tmp_path / "ckpt"),
                         checkpoint_every_rounds=2, comm_round=4)
        sim = build_sim(args2)
        restored = sim.ckpt.latest(sim._ckpt_state())
        assert restored is not None and restored[0] == 3
        assert "defense_state" in restored[1]
        hist = np.asarray(restored[1]["defense_state"]["history"])
        assert np.abs(hist).sum() > 0  # the history came back, not zeros
        sim._load_ckpt_state(restored[1])
        sim.run_round(4, hyper_for(args2))  # donation-safe after restore

    def test_restore_tolerates_missing_defense_state_leaf(self, tmp_path):
        """A checkpoint written WITHOUT a stateful defense (no
        defense_state leaf) must stay loadable when foolsgold is enabled
        on resume: the engine retries without the leaf (cold-start
        history) instead of making the checkpoint unreadable."""
        pytest.importorskip("orbax.checkpoint")
        kw = dict(checkpoint_dir=str(tmp_path / "ckpt"),
                  checkpoint_every_rounds=2)
        fedml_tpu.run_simulation(backend="tpu",
                                 args=sim_args(comm_round=4, **kw))
        r = fedml_tpu.run_simulation(
            backend="tpu", args=sim_args(comm_round=6, enable_defense=True,
                                         defense_type="foolsgold", **kw))
        assert r["final_test_acc"] is not None

    def test_foolsgold_crash_resume_matches_uninterrupted(self, tmp_path):
        """Crash at round 3 (after its checkpoint flushes) + resume must
        land on the SAME params as the uninterrupted run — which can only
        happen if the resumed run restores the similarity history (an
        amnesiac history re-pardons the sybils and diverges)."""
        pytest.importorskip("orbax.checkpoint")
        from fedml_tpu.core.chaos import ChaosCrash
        kw = dict(enable_defense=True, defense_type="foolsgold",
                  enable_attack=True, attack_type="byzantine_flip",
                  byzantine_client_num=2, attack_scale=5.0,
                  comm_round=6, checkpoint_every_rounds=2, random_seed=9)
        full = fedml_tpu.run_simulation(
            backend="tpu",
            args=sim_args(checkpoint_dir=str(tmp_path / "full"), **kw))
        with pytest.raises(ChaosCrash):
            fedml_tpu.run_simulation(
                backend="tpu",
                args=sim_args(checkpoint_dir=str(tmp_path / "crash"),
                              chaos_crash_at_round=3, **kw))
        resumed = fedml_tpu.run_simulation(
            backend="tpu",
            args=sim_args(checkpoint_dir=str(tmp_path / "crash"),
                          chaos_crash_at_round=3, **kw))
        assert_params_close(full["params"], resumed["params"])


class TestContributionFusion:
    """contribution.enabled no longer disqualifies fusion: the round stays
    ONE dispatch (the program emits the post-attack sharded matrix), the
    subset values are evaluated on device, only [K] scores come host."""

    def test_contribution_with_defense_stays_fused_single_dispatch(self):
        args = sim_args(contribution_method="loo", **DEFENSE_KW)
        sim = build_sim(args)
        assert sim.contribution.enabled and sim.robust_fused
        sim.run_round(0, hyper_for(args))
        assert sim.dispatch_stats["dispatches"] == 1  # the round itself
        rec = sim.contribution.history[0]
        assert len(rec["contributions"]) == 8
        assert np.isfinite(rec["contributions"]).all()

    def test_contribution_only_run_fuses_with_mean_kernel(self):
        """No defense configured: the fused program aggregates with the
        mean kernel and still feeds the assessor; blocks fall back to
        per-round dispatches (the assessor needs each round's matrix)."""
        args = sim_args(contribution_method="loo")
        sim = build_sim(args)
        assert sim.robust_mode and sim.robust_fused
        sim.run_rounds_fused(0, 2, hyper_for(args))
        assert len(sim.contribution.history) == 2
        assert sim.dispatch_stats["dispatches"] == 2  # one per round

    def test_contribution_params_parity_fused_vs_host(self):
        """The fused contribution path must not perturb training: params
        match the host-fallback path (collect + host assessment) exactly,
        and both paths rank the same clients."""
        kw = dict(contribution_method="loo", comm_round=2, **DEFENSE_KW)
        r_fused = fedml_tpu.run_simulation(backend="tpu",
                                           args=sim_args(**kw))
        r_host = fedml_tpu.run_simulation(
            backend="tpu", args=sim_args(robust_fused="host",
                                         sharded_defense="false", **kw))
        assert_params_close(r_fused["params"], r_host["params"])

    def test_contribution_values_match_host_fallback(self):
        """Coalition values are computed around the ROUND-START params.
        The fused scores must match the pre-ISSUE-4 host fallback's scores
        — assessing around the post-round params (the round's aggregate
        applied twice) would silently skew every LOO/Shapley value."""
        kw = dict(contribution_method="loo", **DEFENSE_KW)
        sim_f = build_sim(sim_args(**kw))
        sim_h = build_sim(sim_args(robust_fused="host",
                                   sharded_defense="false", **kw))
        assert sim_f.robust_fused and not sim_h.robust_fused
        hyper = hyper_for(sim_args(**kw))
        sim_f.run_round(0, hyper)
        sim_h.run_round(0, hyper)
        cf = np.asarray(sim_f.contribution.history[0]["contributions"])
        ch = np.asarray(sim_h.contribution.history[0]["contributions"])
        np.testing.assert_allclose(cf, ch, atol=1e-5)

    def test_gtg_shapley_rides_fused_path(self):
        args = sim_args(contribution_method="gtg_shapley",
                        shapley_max_perms=4, **DEFENSE_KW)
        sim = build_sim(args)
        assert sim.robust_fused
        sim.run_round(0, hyper_for(args))
        assert len(sim.contribution.history[0]["contributions"]) == 8


class TestCompileCache:
    def test_compile_cache_dir_knob_wires_jax_config(self, tmp_path):
        """Opt-in persistent compilation cache: the knob must land in
        jax.config and create the directory; absent knob changes nothing."""
        cache = tmp_path / "xla-cache"
        prev = jax.config.jax_compilation_cache_dir
        try:
            args = sim_args(compile_cache_dir=str(cache))
            sim = build_sim(args)
            assert jax.config.jax_compilation_cache_dir == str(cache)
            assert cache.is_dir()
            sim.run_round(0, hyper_for(args))  # compiles go through cache
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)

    def test_compile_cache_off_by_default(self):
        args = sim_args()
        assert getattr(args, "compile_cache_dir", None) is None


class TestDonation:
    """params/server_state/client_states are donated to the round
    programs; outputs replace them 1:1, and the engine must never touch a
    donated buffer again."""

    def test_round_donates_and_never_reuses(self):
        # SCAFFOLD keeps per-client state, so the donated client_states
        # buffer is exercised too (FedAvg's is an empty pytree)
        args = sim_args(federated_optimizer="scaffold")
        sim = build_sim(args)
        hyper = hyper_for(args)
        old_params = jax.tree_util.tree_leaves(sim.params)[0]
        old_states = jax.tree_util.tree_leaves(sim.client_states)[0]
        for r in range(3):  # reuse of a donated buffer would raise here
            sim.run_round(r, hyper)
        assert old_params.is_deleted()
        assert old_states.is_deleted()
        stats = sim._evaluate(sim.params, sim.fed.test["x"],
                              sim.fed.test["y"], sim.fed.test["mask"])
        assert np.isfinite(float(stats["loss_sum"]))

    def test_fused_and_robust_paths_donate_safely(self):
        for kw in ({}, dict(**DEFENSE_KW), dict(**DEFENSE_KW, **ATTACK_KW)):
            args = sim_args(**kw)
            sim = build_sim(args)
            hyper = hyper_for(args)
            old = jax.tree_util.tree_leaves(sim.params)[0]
            sim.run_rounds_fused(0, 3, hyper)
            sim.run_rounds_fused(3, 3, hyper)
            assert old.is_deleted()
            assert all(np.isfinite(np.asarray(l)).all()
                       for l in jax.tree_util.tree_leaves(sim.params))

    def test_run_round_after_checkpoint_restore(self, tmp_path):
        """Restored state is freshly device_put — donation in the next
        round must work on it, and the resumed run must finish."""
        pytest.importorskip("orbax.checkpoint")
        kw = dict(checkpoint_dir=str(tmp_path / "ckpt"),
                  checkpoint_every_rounds=2, comm_round=4)
        fedml_tpu.run_simulation(backend="tpu", args=sim_args(**kw))
        args = sim_args(**kw)
        sim = build_sim(args)  # restores round 3 checkpoint
        restored = sim.ckpt.latest(sim._ckpt_state())
        assert restored is not None and restored[0] == 3
        sim._load_ckpt_state(restored[1])
        sim.run_round(4, hyper_for(args))
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree_util.tree_leaves(sim.params))

    def test_donation_off_knob(self):
        args = sim_args(donate_buffers=False)
        sim = build_sim(args)
        old = jax.tree_util.tree_leaves(sim.params)[0]
        sim.run_round(0, hyper_for(args))
        assert not old.is_deleted()


class TestCompileStability:
    """Canonical schedule width: the fused programs compile exactly once
    per run, even when per-round schedules disagree on width."""

    def test_fused_blocks_compile_once(self, xla_compile_counter):
        # subsampled rounds (8 of 16) make per-round schedule widths vary
        # — the canonical-width padding must absorb that
        args = sim_args(client_num_in_total=16, client_num_per_round=8)
        sim = build_sim(args)
        hyper = hyper_for(args)
        sim.run_rounds_fused(0, 4, hyper)  # warmup compiles everything
        assert sim.dispatch_stats["compiles"] >= 1
        xla_compile_counter.reset()
        sim.run_rounds_fused(4, 4, hyper)
        sim.run_rounds_fused(8, 4, hyper)
        assert xla_compile_counter.delta() == 0
        assert sim.dispatch_stats["dispatches"] == 3

    def test_robust_fused_blocks_compile_once(self, xla_compile_counter):
        args = sim_args(client_num_in_total=16, client_num_per_round=8,
                        **DEFENSE_KW, **ATTACK_KW)
        sim = build_sim(args)
        assert sim.robust_fused
        hyper = hyper_for(args)
        sim.run_rounds_fused(0, 4, hyper)
        xla_compile_counter.reset()
        sim.run_rounds_fused(4, 4, hyper)
        sim.run_rounds_fused(8, 4, hyper)
        assert xla_compile_counter.delta() == 0

    @pytest.mark.parametrize("defense", ["bulyan", "rfa", "foolsgold"])
    def test_new_defense_8round_block_compiles_once(
            self, defense, xla_compile_counter):
        """ISSUE 4 acceptance pin: an 8-round fused block with each newly
        fused defense compiles exactly ONE program (the compile counter
        reads 1), and later blocks add zero compiles — stateful history
        threading must not break the canonical-width invariant."""
        args = sim_args(client_num_in_total=16, client_num_per_round=8,
                        enable_defense=True, defense_type=defense,
                        byzantine_client_num=2)
        sim = build_sim(args)
        assert sim.robust_fused
        hyper = hyper_for(args)
        sim.run_rounds_fused(0, 8, hyper)
        assert sim.dispatch_stats["dispatches"] == 1
        assert sim.dispatch_stats["compiles"] == 1
        xla_compile_counter.reset()
        sim.run_rounds_fused(8, 8, hyper)
        assert xla_compile_counter.delta() == 0
        assert sim.dispatch_stats["compiles"] == 1  # still 1: no recompile

    def test_digits_8round_fused_compile_count_pinned(
            self, xla_compile_counter):
        """Regression pin (ISSUE 2 satellite): an 8-round fused digits
        run compiles its fused program exactly ONCE, and later blocks add
        zero compiles — the engine's recompile counter must read 1 across
        the whole multi-block run."""
        pytest.importorskip("sklearn")
        args = sim_args(dataset="digits", client_num_in_total=10,
                        client_num_per_round=10, learning_rate=0.3)
        sim = build_sim(args)
        hyper = hyper_for(args)
        sim.run_rounds_fused(0, 8, hyper)
        # the traced dispatch compiled exactly one program: the fused round
        assert sim.dispatch_stats["compiles"] == 1
        xla_compile_counter.reset()
        sim.run_rounds_fused(8, 8, hyper)
        sim.run_rounds_fused(16, 8, hyper)
        assert xla_compile_counter.delta() == 0
        assert sim.dispatch_stats["compiles"] == 1  # still 1: no recompile


class TestObservability:
    def test_dispatch_records_reach_mlops_sink(self, tmp_path):
        import json
        from fedml_tpu.core import mlops
        args = sim_args(run_id="disp-test", log_file_dir=str(tmp_path))
        mlops.init(args)
        try:
            sim = build_sim(args)
            sim.run_rounds_fused(0, 2, hyper_for(args))
        finally:
            mlops.init(Arguments(enable_tracking=False))
        records = [json.loads(l) for l in
                   (tmp_path / "run_disp-test.jsonl").read_text()
                   .splitlines()]
        disp = [r for r in records if r.get("kind") == "dispatch"]
        assert disp, records
        assert {"dispatch", "wall_s", "rounds", "compiles"} <= set(disp[0])
        assert disp[0]["rounds"] == 2

    def test_round_cost_flops_warns_once(self, caplog):
        from types import SimpleNamespace
        args = sim_args()
        sim = build_sim(args)

        def boom(*a, **k):
            raise RuntimeError("boom")

        sim.spec = SimpleNamespace(loss=boom)
        with caplog.at_level(logging.WARNING,
                             logger="fedml_tpu.simulation.tpu.engine"):
            assert sim.round_cost_flops(hyper_for(args)) == 0.0
            assert sim.round_cost_flops(hyper_for(args)) == 0.0
        warned = [r for r in caplog.records
                  if "round_cost_flops" in r.getMessage()]
        assert len(warned) == 1
        assert "boom" in warned[0].getMessage()
