"""Authenticated pairwise channels (core/mpc/channels.py): the crypto the
SecAgg/LSA runtimes rely on so the server routes only ciphertext."""

import numpy as np
import pytest

pytest.importorskip(
    "cryptography",
    reason="core/mpc/channels.py needs the cryptography package (not"
           " bundled in every runtime image)")

from fedml_tpu.core.mpc import channels


def test_seal_open_roundtrip():
    sk_a, pk_a = channels.keygen()
    sk_b, pk_b = channels.keygen()
    blob = channels.seal(sk_a, pk_b, b"share payload",
                         aad=channels.pair_aad(0, 1))
    assert b"share payload" not in blob
    out = channels.open_sealed(sk_b, pk_a, blob, aad=channels.pair_aad(0, 1))
    assert out == b"share payload"


def test_open_fails_for_third_party_and_wrong_slot():
    sk_a, pk_a = channels.keygen()
    sk_b, pk_b = channels.keygen()
    sk_eve, pk_eve = channels.keygen()
    blob = channels.seal(sk_a, pk_b, b"secret", aad=channels.pair_aad(0, 1))
    # an eavesdropper (the routing server) cannot open it
    with pytest.raises(channels.DecryptError):
        channels.open_sealed(sk_eve, pk_a, blob, aad=channels.pair_aad(0, 1))
    # the right recipient under a replayed (sender, receiver) slot cannot
    with pytest.raises(channels.DecryptError):
        channels.open_sealed(sk_b, pk_a, blob, aad=channels.pair_aad(2, 1))
    # tampering is detected
    bad = blob[:-1] + bytes([blob[-1] ^ 1])
    with pytest.raises(channels.DecryptError):
        channels.open_sealed(sk_b, pk_a, bad, aad=channels.pair_aad(0, 1))


def test_mask_seed_symmetric_and_pair_specific():
    sk_a, pk_a = channels.keygen()
    sk_b, pk_b = channels.keygen()
    sk_c, pk_c = channels.keygen()
    s_ab = channels.mask_seed(sk_a, pk_b)
    s_ba = channels.mask_seed(sk_b, pk_a)
    assert s_ab == s_ba  # ECDH symmetry: both ends derive the same seed
    # 128-bit seed space: the PRG expands outputs in GF(2^31-1) but the
    # seed itself must not collapse to 31 bits (ADVICE r3)
    assert 0 <= s_ab < (1 << 128)
    assert s_ab.bit_length() > 64
    assert channels.mask_seed(sk_a, pk_c) != s_ab


def test_key_limb_roundtrip_survives_shamir():
    from fedml_tpu.core.mpc import shamir_reconstruct, shamir_share
    rng = np.random.RandomState(0)
    sk, pk = channels.keygen()
    limbs = channels.key_to_limbs(sk)
    assert len(limbs) == channels.KEY_LIMBS
    # share every limb 5-of-3 and reconstruct from a random subset
    rec_limbs = []
    for limb in limbs:
        shares = shamir_share(limb, 5, 3, rng)
        rec_limbs.append(shamir_reconstruct([shares[4], shares[1],
                                             shares[2]]))
    sk2 = channels.limbs_to_key(rec_limbs)
    # the reconstructed key produces identical ECDH results
    peer_sk, peer_pk = channels.keygen()
    assert (channels.mask_seed(sk2, peer_pk)
            == channels.mask_seed(sk, peer_pk))
    assert channels.private_bytes(sk2) == channels.private_bytes(sk)
