"""Protocol-level optimizers: hierarchical, async, decentralized gossip,
split learning, vertical FL — each must learn on the synthetic task."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments

pytestmark = __import__('pytest').mark.slow


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=8, client_num_per_round=8,
                comm_round=6, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=3, random_seed=17)
    base.update(kw)
    return Arguments(**base)


def test_hierarchical_learns():
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        federated_optimizer="HierarchicalFL", group_num=2,
        group_comm_round=2, comm_round=4))
    assert r["final_test_acc"] > 0.6, r["history"]


def test_async_fedavg_learns_with_staleness():
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        federated_optimizer="Async_FedAvg", comm_round=24,
        client_num_per_round=4))
    assert r["final_test_acc"] > 0.6, r["history"][-1]
    # staleness actually occurred (heterogeneous durations guarantee it)
    assert any(rec.get("staleness", 0) > 0 for rec in r["history"])


def test_decentralized_gossip_converges_and_reaches_consensus():
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        federated_optimizer="decentralized_fl", comm_round=8,
        topology_neighbors=2))
    assert r["final_test_acc"] > 0.6, r["history"]
    dists = [rec["consensus_dist"] for rec in r["history"]
             if "consensus_dist" in rec]
    assert dists[-1] < dists[0] * 2  # mixing keeps nodes from diverging


def test_split_nn_learns():
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        federated_optimizer="split_nn", client_num_in_total=4, comm_round=3,
        learning_rate=0.05))
    assert r["final_test_acc"] > 0.6, r["history"]


def test_vertical_fl_learns():
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        federated_optimizer="classical_vertical", party_num=3, comm_round=5,
        learning_rate=0.05))
    assert r["final_test_acc"] > 0.6, r["history"]


def test_fedgan_generator_fools_discriminator():
    """FedGAN: averaged (G, D) training drives D's real-vs-fake accuracy
    down from ~1.0 toward chance as G learns the data manifold."""
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        model="gan", federated_optimizer="FedGAN", comm_round=4,
        client_num_in_total=4, client_num_per_round=4,
        learning_rate=2e-4, batch_size=32))
    assert len(r["history"]) == 4
    assert all(np.isfinite(h["g_loss"]) for h in r["history"])
    # D should not perfectly separate by the end (G is learning)
    assert r["final_disc_acc"] < 0.995


def test_fedgkt_learns_via_feature_exchange():
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        federated_optimizer="FedGKT", client_num_in_total=4,
        comm_round=4))
    assert r["final_test_acc"] > 0.6, r["history"]
    # KD actually moves the server: accuracy improves over rounds
    assert r["history"][-1]["test_acc"] >= r["history"][0]["test_acc"]


def test_fednas_searches_and_learns():
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        federated_optimizer="FedNAS", client_num_in_total=4,
        comm_round=4, learning_rate=0.05))
    assert r["final_test_acc"] > 0.6, r["history"]
    arch = r["architecture"]
    assert len(arch) == 2 and all(op != "zero" for op in arch), arch


def test_fedseg_miou_improves():
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        dataset="synthetic_seg", federated_optimizer="FedSeg",
        client_num_in_total=4, client_num_per_round=4, comm_round=6,
        learning_rate=0.2, batch_size=16))
    assert r["final_miou"] > 0.5, r["history"]
    assert r["history"][-1]["miou"] > r["history"][0]["miou"]


def test_turbo_aggregate_matches_fedavg():
    """The group-ring masked aggregation must be FedAvg-exact (masks cancel,
    fixed-point error only)."""
    args = make_args(federated_optimizer="turbo_aggregate",
                     client_num_in_total=6, client_num_per_round=6,
                     comm_round=4, turbo_groups=2)
    r = fedml_tpu.run_simulation(backend="sp", args=args)
    assert r["final_test_acc"] > 0.6, r["history"]


class TestRealShakespeareNWP:
    def test_fedopt_rnn_learns_real_shakespeare(self, tmp_path):
        """Real-language NWP end-to-end (reference fed_shakespeare + rnn +
        FedOpt): the bundled role-partitioned Shakespeare shard through the
        LEAF reader, a 2-layer LSTM, FedOpt with a momentum server. The
        model must beat the majority-character baseline (~0.19, predicting
        space) on held-out text."""
        args = Arguments(dataset="shakespeare", model="rnn",
                         client_num_in_total=10, client_num_per_round=10,
                         comm_round=16, epochs=2, batch_size=16,
                         learning_rate=0.4, federated_optimizer="fedopt",
                         server_optimizer="sgd", server_lr=1.0,
                         server_momentum=0.9, frequency_of_the_test=4,
                         random_seed=0, data_cache_dir=str(tmp_path))
        r = fedml_tpu.run_simulation(backend="tpu", args=args)
        assert r["final_test_acc"] > 0.21, [
            h.get("test_acc") for h in r["history"] if "test_acc" in h]
