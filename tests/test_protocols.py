"""Protocol-level optimizers: hierarchical, async, decentralized gossip,
split learning, vertical FL — each must learn on the synthetic task."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=8, client_num_per_round=8,
                comm_round=6, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=3, random_seed=17)
    base.update(kw)
    return Arguments(**base)


def test_hierarchical_learns():
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        federated_optimizer="HierarchicalFL", group_num=2,
        group_comm_round=2, comm_round=4))
    assert r["final_test_acc"] > 0.6, r["history"]


def test_async_fedavg_learns_with_staleness():
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        federated_optimizer="Async_FedAvg", comm_round=24,
        client_num_per_round=4))
    assert r["final_test_acc"] > 0.6, r["history"][-1]
    # staleness actually occurred (heterogeneous durations guarantee it)
    assert any(rec.get("staleness", 0) > 0 for rec in r["history"])


def test_decentralized_gossip_converges_and_reaches_consensus():
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        federated_optimizer="decentralized_fl", comm_round=8,
        topology_neighbors=2))
    assert r["final_test_acc"] > 0.6, r["history"]
    dists = [rec["consensus_dist"] for rec in r["history"]
             if "consensus_dist" in rec]
    assert dists[-1] < dists[0] * 2  # mixing keeps nodes from diverging


def test_split_nn_learns():
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        federated_optimizer="split_nn", client_num_in_total=4, comm_round=3,
        learning_rate=0.05))
    assert r["final_test_acc"] > 0.6, r["history"]


def test_vertical_fl_learns():
    r = fedml_tpu.run_simulation(backend="sp", args=make_args(
        federated_optimizer="classical_vertical", party_num=3, comm_round=5,
        learning_rate=0.05))
    assert r["final_test_acc"] > 0.6, r["history"]
