"""MLOps agent daemons over the pub/sub broker: master dispatches
start/stop-train over topics, slave executes via the run registry and
streams status back, last-will flags dead agents."""

import os
import textwrap
import time

import pytest

from fedml_tpu.agents import (DEVICE_IDLE, DEVICE_OFFLINE, JOB_FINISHED,
                              JOB_KILLED, JOB_RUNNING, MasterAgent,
                              SlaveAgent, launch_job_remote)
from fedml_tpu.core.distributed.communication.pubsub import PubSubBroker


@pytest.fixture()
def registry(tmp_path, monkeypatch):
    monkeypatch.setenv("FEDML_TPU_RUNS_DIR", str(tmp_path / "runs"))
    # daemons are secure-by-default: a bind token is part of any deployment
    monkeypatch.setenv("FEDML_TPU_AGENT_SECRET", "test-bind-token")
    return tmp_path


@pytest.fixture()
def cluster(registry):
    broker = PubSubBroker()
    master = MasterAgent("127.0.0.1", broker.port)
    master.start()
    slave = SlaveAgent(device_id=7, broker_host="127.0.0.1",
                       broker_port=broker.port, poll_s=0.1)
    slave.start()
    assert master.wait_for_device(7, DEVICE_IDLE, timeout_s=10) == DEVICE_IDLE
    yield broker, master, slave
    slave.stop()
    master.stop()
    broker.stop()


def _job_yaml(tmp_path, body: str, name="job.yaml") -> str:
    path = tmp_path / name
    path.write_text(textwrap.dedent(body))
    return str(path)


def test_remote_launch_to_finished(cluster, registry):
    _, master, _ = cluster
    yml = _job_yaml(registry, """
        job: echo agent-ran > out.txt
        workspace: .
    """)
    info = launch_job_remote(yml, device_id=7, master=master, timeout_s=30)
    assert info["status"] == JOB_FINISHED, info
    # the full FSM was streamed: PROVISIONING -> RUNNING -> FINISHED
    seen = [h["status"] for h in info["history"]]
    assert seen[0] == "PROVISIONING" and JOB_RUNNING in seen
    # yaml CONTENT was shipped: the job ran in the AGENT's job dir, not in
    # the master-side yaml's directory
    out = (registry / "runs" / "agent_7" / "jobs" / info["request_id"]
           / "out.txt")
    assert out.read_text().strip() == "agent-ran"
    assert not (registry / "out.txt").exists()


def test_remote_stop_kills_run(cluster, registry):
    _, master, _ = cluster
    yml = _job_yaml(registry, """
        job: sleep 60
        workspace: .
    """)
    rid = master.dispatch(7, yml)
    assert master.wait_for_status(rid, JOB_RUNNING, timeout_s=30) \
        == JOB_RUNNING
    master.stop_job(rid)
    assert master.wait_for_status(rid, {JOB_KILLED}, timeout_s=30) \
        == JOB_KILLED


def test_bad_job_reports_failed(cluster, registry):
    _, master, _ = cluster
    info = launch_job_remote(str(registry / "missing.yaml"), device_id=7,
                             master=master, timeout_s=30)
    assert info["status"] == "FAILED"


def test_last_will_marks_device_offline(registry):
    broker = PubSubBroker()
    master = MasterAgent("127.0.0.1", broker.port)
    master.start()
    slave = SlaveAgent(device_id=3, broker_host="127.0.0.1",
                       broker_port=broker.port)
    slave.start()
    assert master.wait_for_device(3, DEVICE_IDLE, timeout_s=10) == DEVICE_IDLE
    # abnormal disconnect (no goodbye): the broker fires the last-will
    slave.center.stop(graceful=False)
    assert master.wait_for_device(3, DEVICE_OFFLINE, timeout_s=10) \
        == DEVICE_OFFLINE
    master.stop()
    broker.stop()


def test_message_center_records_sent(cluster, registry):
    _, master, slave = cluster
    yml = _job_yaml(registry, """
        job: "true"
        workspace: .
    """)
    launch_job_remote(yml, device_id=7, master=master, timeout_s=30)
    rec = registry / "runs" / "agent_7" / "message-sent-success-records.log"
    deadline = time.time() + 5
    while time.time() < deadline and not rec.exists():
        time.sleep(0.1)
    assert rec.exists() and rec.read_text().strip()


def test_cli_agent_and_remote_launch(registry):
    """Full process-level path: `fedml_tpu.cli agent` daemon subprocess +
    `launch --remote` through the broker."""
    import subprocess
    import sys

    broker = PubSubBroker()
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["FEDML_TPU_RUNS_DIR"] = os.environ["FEDML_TPU_RUNS_DIR"]
    agent_proc = subprocess.Popen(
        [sys.executable, "-m", "fedml_tpu.cli", "agent",
         "--broker", f"127.0.0.1:{broker.port}", "--device-id", "9"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        yml = _job_yaml(registry, """
            job: echo cli-remote-ok > cli_out.txt
            workspace: .
        """, name="cli_job.yaml")
        out = subprocess.run(
            [sys.executable, "-m", "fedml_tpu.cli", "launch", yml,
             "--remote", f"127.0.0.1:{broker.port}", "--device-id", "9"],
            env=env, capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "FINISHED" in out.stdout
        hits = list((registry / "runs" / "agent_9" / "jobs").glob(
            "*/cli_out.txt"))
        assert hits and hits[0].read_text().strip() == "cli-remote-ok"
    finally:
        agent_proc.terminate()
        agent_proc.wait(timeout=10)
        broker.stop()


class TestAuth:
    """Broker HMAC handshake + agent bind token (VERDICT r3 item 9):
    unauthenticated peers cannot connect, and even an authenticated broker
    peer cannot start jobs without the agent secret."""

    def test_unauthenticated_connection_refused(self):
        import socket
        from fedml_tpu.core.distributed.communication.pubsub import (
            PubSubBroker, _recv_frame, _send_frame, client_connect)

        broker = PubSubBroker(secret=b"hunter2")
        try:
            # no auth answer -> broker closes before honoring any frame
            raw = socket.create_connection(("127.0.0.1", broker.port))
            hello = _recv_frame(raw)
            assert hello["auth_required"] is True
            _send_frame(raw, {"kind": "sub", "topic": "x"})  # not an auth
            assert _recv_frame(raw) == {"kind": "auth_result", "ok": False}
            assert _recv_frame(raw) is None  # connection dropped
            raw.close()
            # wrong secret -> explicit reject + dropped; client_connect
            # surfaces it as PermissionError
            with pytest.raises(PermissionError):
                client_connect("127.0.0.1", broker.port, b"wrong")
            # right secret -> usable pub/sub
            a = client_connect("127.0.0.1", broker.port, b"hunter2")
            b = client_connect("127.0.0.1", broker.port, b"hunter2")
            _send_frame(a, {"kind": "sub", "topic": "t"})
            time.sleep(0.2)
            _send_frame(b, {"kind": "pub", "topic": "t", "payload": b"hi"})
            got = _recv_frame(a)
            assert got["payload"] == b"hi"
            a.close()
            b.close()
        finally:
            broker.stop()

    def test_unsigned_start_train_refused(self, registry, monkeypatch):
        import json as _json
        from fedml_tpu.agents import MessageCenter, sign_job
        monkeypatch.setenv("FEDML_TPU_AGENT_SECRET", "bind-token")
        broker = PubSubBroker()
        statuses = []
        try:
            slave = SlaveAgent(device_id=9, broker_host="127.0.0.1",
                               broker_port=broker.port, poll_s=0.1)
            slave.start()
            spy = MessageCenter("127.0.0.1", broker.port)
            spy.subscribe("fl_client/mlops/status",
                          lambda p: statuses.append(p))
            spy.start()
            time.sleep(0.3)
            # forged start_train without the bind token
            spy.publish("flclient_agent/9/start_train",
                        {"request_id": "evil", "job_yaml_content": "x"})
            deadline = time.time() + 5
            while time.time() < deadline and not any(
                    s.get("request_id") == "evil" for s in statuses):
                time.sleep(0.1)
            evil = [s for s in statuses if s.get("request_id") == "evil"]
            assert evil and evil[-1]["status"] == "FAILED"
            assert "bind token" in evil[-1].get("error", "")
            # no run was provisioned
            assert slave.runs == {}
            # a signed stop for an unknown run is still honored (verify_job
            # passes with the right secret)
            assert sign_job({"request_id": "r"}).get("auth")
            spy.stop()
            slave.stop()
        finally:
            broker.stop()

    def test_tokenless_daemon_start_refused(self, registry, monkeypatch):
        """VERDICT r4 item 5: open deployment must be an explicit flag.
        Without FEDML_TPU_AGENT_SECRET the daemon refuses to construct,
        and the CLI exits 2 with the reason; insecure_open=True is the
        explicit opt-out."""
        monkeypatch.delenv("FEDML_TPU_AGENT_SECRET", raising=False)
        with pytest.raises(RuntimeError, match="bind token"):
            SlaveAgent(device_id=1, broker_host="127.0.0.1",
                       broker_port=1)  # never connects: ctor refuses first
        # explicit opt-out constructs fine (no broker contact yet)
        SlaveAgent(device_id=1, broker_host="127.0.0.1", broker_port=1,
                   insecure_open=True)
        # process-level: the CLI refuses too
        import subprocess
        import sys
        env = dict(os.environ)
        env.pop("FEDML_TPU_AGENT_SECRET", None)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        out = subprocess.run(
            [sys.executable, "-m", "fedml_tpu.cli", "agent",
             "--broker", "127.0.0.1:1", "--device-id", "1"],
            env=env, capture_output=True, text=True, timeout=60)
        assert out.returncode == 2
        assert "bind token" in out.stderr

    def test_replayed_signed_command_rejected(self, monkeypatch):
        """ADVICE r4: a captured signed frame must not be honored twice
        (nonce/MAC ledger) nor after the freshness window (timestamp)."""
        import time as _time
        from fedml_tpu.agents import (JOB_MAC_TTL_S, sign_job, verify_job)
        monkeypatch.setenv("FEDML_TPU_AGENT_SECRET", "tok")
        signed = sign_job({"request_id": "r1", "job_yaml": "x"})
        ledger = {}
        assert verify_job(signed, seen_macs=ledger) is True
        # exact replay of the captured frame: rejected by the ledger
        assert verify_job(signed, seen_macs=ledger) is False
        # stale frame (signed outside the freshness window): rejected even
        # with an empty ledger
        old = dict(signed)
        monkeypatch.setattr(_time, "time",
                            lambda: old["ts"] + JOB_MAC_TTL_S + 1)
        assert verify_job(old, seen_macs={}) is False
        # tampered-after-signing ts fails the MAC itself
        forged = dict(signed)
        forged["ts"] = signed["ts"] + 1
        assert verify_job(forged, seen_macs={}) is False

    def test_replay_ledger_survives_daemon_restart(self, registry,
                                                   monkeypatch):
        """A frame accepted before a crash must still be rejected by the
        relaunched daemon (the ledger is persisted, not process memory)."""
        from fedml_tpu.agents import sign_job
        signed = sign_job({"request_id": "r1", "job_yaml": "x"})
        a1 = SlaveAgent(device_id=2, broker_host="127.0.0.1", broker_port=1)
        assert a1._check(signed) is None           # first delivery: accepted
        assert "already seen" in a1._check(signed)  # same-process replay
        a2 = SlaveAgent(device_id=2, broker_host="127.0.0.1", broker_port=1)
        assert "already seen" in a2._check(signed)  # post-restart replay

    def test_redelivered_start_reannounces_instead_of_failing(
            self, registry):
        """A byte-identical redelivery of an honored start_train (sender
        retry or replay) must re-announce the live job, not publish FAILED
        and poison its status on the master."""
        from fedml_tpu.agents import JOB_FINISHED, sign_job
        a = SlaveAgent(device_id=4, broker_host="127.0.0.1", broker_port=1)
        signed = sign_job({"request_id": "live", "job_yaml_content": "x"})
        # simulate the already-honored state without launching anything
        assert a._check(signed) is None
        a._seen_requests.add("live")
        a.runs["live"] = "run-1"
        a._status("live", JOB_RUNNING, run_id="run-1")
        a._on_start(dict(signed))  # exact redelivery
        statuses = [q["payload"] for q in a.center._queue
                    if q["payload"].get("request_id") == "live"]
        assert statuses and statuses[-1]["status"] == JOB_RUNNING
        assert all(s["status"] != "FAILED" for s in statuses)
        # a redelivery AFTER the job finished re-announces FINISHED — it
        # must not resurrect the job to RUNNING on the master
        a._status("live", JOB_FINISHED, run_id="run-1")
        a._on_start(dict(signed))
        statuses = [q["payload"] for q in a.center._queue
                    if q["payload"].get("request_id") == "live"]
        assert statuses[-1]["status"] == JOB_FINISHED
        # a replayed frame for an UNKNOWN request is dropped silently
        # (no status poisoning), not FAILED
        n_before = len(a.center._queue)
        other = sign_job({"request_id": "gone", "job_yaml_content": "x"})
        assert a._check(other) is None  # consume its MAC into the ledger
        a._on_start(dict(other))        # now arrives as a replay
        poisoned = [q["payload"] for q in a.center._queue[n_before:]
                    if q["payload"].get("request_id") == "gone"]
        assert poisoned == []

    def test_unauthenticated_frame_cannot_poison_live_job(self, registry):
        """An unauthenticated peer echoing a LIVE request id must not be
        able to flip that job to FAILED on the master; unknown ids still
        get the refusal status so misconfigured senders aren't left
        hanging."""
        a = SlaveAgent(device_id=6, broker_host="127.0.0.1", broker_port=1)
        a._seen_requests.add("live")
        a._status("live", JOB_RUNNING, run_id="run-9")
        n_before = len(a.center._queue)
        a._on_start({"request_id": "live"})  # forged, no MAC
        assert all(q["payload"]["status"] != "FAILED"
                   for q in a.center._queue[n_before:]
                   if q["payload"].get("request_id") == "live")
        a._on_start({"request_id": "fresh"})  # forged, unknown id
        fresh = [q["payload"] for q in a.center._queue
                 if q["payload"].get("request_id") == "fresh"]
        assert fresh and fresh[-1]["status"] == "FAILED"


class TestAccountRegistry:
    """Device-binding account registry (reference account_manager.py):
    devices enroll with an API key, get a one-time token, and a
    registry-wired master only accepts presence from bound devices."""

    def test_register_verify_revoke(self, registry):
        from fedml_tpu.agents.accounts import AccountRegistry
        reg = AccountRegistry(str(registry / "acc.db"))
        did, token = reg.register_device("api-key-1", device_id="11")
        assert reg.verify_device(did, token) is True
        assert reg.verify_device(did, "wrong") is False
        assert reg.verify_device("ghost", token) is False
        # same api key -> same account for a second device
        did2, _ = reg.register_device("api-key-1")
        accounts = {d["account_id"] for d in reg.devices()}
        assert len(accounts) == 1
        assert reg.revoke_device(did) is True
        assert reg.verify_device(did, token) is False  # revoked

    def test_reregister_and_revoked_ids_stay_dead(self, registry):
        """Re-binding an existing device id (any key) must be refused —
        otherwise a revocation could be undone or an identity hijacked."""
        from fedml_tpu.agents.accounts import AccountRegistry
        reg = AccountRegistry(str(registry / "acc3.db"))
        did, token = reg.register_device("key-a", device_id="77")
        with pytest.raises(ValueError, match="already registered"):
            reg.register_device("key-b", device_id="77")
        reg.revoke_device(did)
        with pytest.raises(ValueError, match="already registered"):
            reg.register_device("key-a", device_id="77")
        assert reg.verify_device(did, token) is False
        # generated ids are numeric (agent topics address ints)
        gen_id, _ = reg.register_device("key-a")
        assert gen_id.isdigit()

    def test_status_from_unbound_device_dropped(self, registry):
        """With a registry wired, a broker peer must not conjure a
        dispatchable device (or poison versions) via the status topic."""
        from fedml_tpu.agents import MessageCenter
        from fedml_tpu.agents.accounts import AccountRegistry
        reg = AccountRegistry(str(registry / "acc4.db"))
        broker = PubSubBroker()
        try:
            master = MasterAgent("127.0.0.1", broker.port, registry=reg)
            master.start()
            spy = MessageCenter("127.0.0.1", broker.port)
            spy.start()
            spy.publish("fl_client/mlops/status", {
                "device_id": 44, "request_id": "x", "status": "FINISHED"})
            spy.publish("fl_client/mlops/status", {
                "device_id": 44, "request_id": "y", "status": "UPGRADED",
                "version": "evil"})
            time.sleep(0.8)
            assert 44 not in master.devices
            assert all(d["version"] != "evil" for d in reg.devices())
            spy.stop()
            master.stop()
        finally:
            broker.stop()

    def test_schema_migration_from_pre_mac_key_db(self, registry):
        """An accounts.db created before the mac_key column must open,
        migrate, and degrade gracefully (old devices fail proofs —
        re-enroll — instead of crashing every presence callback)."""
        import sqlite3
        from fedml_tpu.agents.accounts import AccountRegistry
        path = str(registry / "old.db")
        con = sqlite3.connect(path)
        con.execute("""CREATE TABLE devices (
            device_id TEXT PRIMARY KEY, account_id TEXT NOT NULL,
            token_salt TEXT NOT NULL, token_hash TEXT NOT NULL,
            registered REAL NOT NULL, last_seen REAL,
            revoked INTEGER DEFAULT 0, version TEXT DEFAULT '')""")
        con.execute("INSERT INTO devices VALUES "
                    "('9', 'a', 's', 'h', 1.0, NULL, 0, '')")
        con.commit()
        con.close()
        reg = AccountRegistry(path)  # migrates
        assert reg.verify_presence("9", "IDLE", 1.0, "n", "p") is False
        did, token = reg.register_device("k")  # new enrolls still work
        from fedml_tpu.agents.accounts import presence_proof
        import time as _t
        ts = _t.time()
        assert reg.verify_presence(did, "IDLE", ts, "n1",
                                   presence_proof(token, did, "IDLE",
                                                  ts, "n1"))

    def test_replayed_presence_nonce_rejected(self, registry):
        """A harvested presence proof (incl. the freshness-exempt LWT)
        is single-use at the master."""
        from fedml_tpu.agents import MessageCenter
        from fedml_tpu.agents.accounts import (AccountRegistry,
                                               presence_proof)
        import time as _t
        reg = AccountRegistry(str(registry / "acc6.db"))
        did, token = reg.register_device("k", device_id="31")
        broker = PubSubBroker()
        try:
            master = MasterAgent("127.0.0.1", broker.port, registry=reg)
            master.start()
            spy = MessageCenter("127.0.0.1", broker.port)
            spy.start()
            ts = _t.time()
            frame = {"device_id": 31, "status": "OFFLINE", "ts": ts,
                     "nonce": "nn", "proof": presence_proof(
                         token, "31", "OFFLINE", ts, "nn")}
            spy.publish("fl_client/agent/online", dict(frame))
            assert master.wait_for_device(31, "OFFLINE", timeout_s=10) \
                == "OFFLINE"
            # device comes back IDLE; the replayed OFFLINE must not land
            ts2 = _t.time()
            spy.publish("fl_client/agent/online", {
                "device_id": 31, "status": "IDLE", "ts": ts2,
                "nonce": "n2", "proof": presence_proof(
                    token, "31", "IDLE", ts2, "n2")})
            assert master.wait_for_device(31, "IDLE", timeout_s=10) \
                == "IDLE"
            spy.publish("fl_client/agent/online", dict(frame))  # replay
            time.sleep(0.6)
            assert master.devices[31]["status"] == "IDLE"
            spy.stop()
            master.stop()
        finally:
            broker.stop()

    def test_heartbeat_does_not_clobber_running_device(self, registry):
        """A presence heartbeat must not erase the master's running-jobs
        bookkeeping (it would make schedulers dispatch onto a busy
        device)."""
        broker = PubSubBroker()
        try:
            master = MasterAgent("127.0.0.1", broker.port)
            master.start()
            slave = SlaveAgent(device_id=8, broker_host="127.0.0.1",
                               broker_port=broker.port, poll_s=0.1)
            slave.start(presence_interval_s=0.2)
            assert master.wait_for_device(8, DEVICE_IDLE, timeout_s=10) \
                == DEVICE_IDLE
            yml = _job_yaml(registry, """
                job: sleep 30
                workspace: .
            """, name="busy.yaml")
            rid = master.dispatch(8, yml)
            assert master.wait_for_status(rid, JOB_RUNNING,
                                          timeout_s=30) == JOB_RUNNING
            time.sleep(0.8)  # several heartbeats later...
            assert master.devices[8]["status"] == "RUNNING"
            master.stop_job(rid)
            master.wait_for_status(rid, {JOB_KILLED}, timeout_s=30)
            slave.stop()
            master.stop()
        finally:
            broker.stop()

    def test_master_drops_unbound_presence(self, registry):
        from fedml_tpu.agents.accounts import AccountRegistry
        reg = AccountRegistry(str(registry / "acc2.db"))
        _, token = reg.register_device("k", device_id="5")
        broker = PubSubBroker()
        try:
            master = MasterAgent("127.0.0.1", broker.port, registry=reg)
            master.start()
            # unbound device: no token
            rogue = SlaveAgent(device_id=6, broker_host="127.0.0.1",
                               broker_port=broker.port)
            rogue.start()
            # bound device: enrolled token
            bound = SlaveAgent(device_id=5, broker_host="127.0.0.1",
                               broker_port=broker.port,
                               device_token=token)
            bound.start()
            assert master.wait_for_device(5, DEVICE_IDLE, timeout_s=10) \
                == DEVICE_IDLE
            assert 6 not in master.devices  # rogue presence dropped
            rogue.stop()
            bound.stop()
            master.stop()
        finally:
            broker.stop()


class TestOTAUpgrade:
    """OTA agent upgrade (reference scheduler_core/ota_upgrade.py):
    signed package with sha256, staged under the agent dir, version
    recorded; bad digests and unsigned commands are refused."""

    def _package(self, tmp, content="print('v2')"):
        import hashlib
        import zipfile
        pkg = tmp / "agent_v2.zip"
        with zipfile.ZipFile(pkg, "w") as z:
            z.writestr("fedml_tpu_ext/__init__.py", content)
        blob = pkg.read_bytes()
        return str(pkg), hashlib.sha256(blob).hexdigest()

    def test_upgrade_staged_and_version_recorded(self, cluster, registry):
        import json as _json
        _, master, slave = cluster
        pkg, _sha = self._package(registry)
        rid = master.dispatch_upgrade(7, pkg, version="2.0.0")
        assert master.wait_for_status(rid, {"UPGRADED"}, timeout_s=20) \
            == "UPGRADED"
        assert slave.current_version == "2.0.0"
        staged = (registry / "runs" / "agent_7" / "pkgs" / "2.0.0"
                  / "fedml_tpu_ext" / "__init__.py")
        assert staged.exists()
        cur = _json.loads((registry / "runs" / "agent_7"
                           / "current_version.json").read_text())
        assert cur["version"] == "2.0.0"

    def test_bad_digest_refused(self, cluster, registry):
        import base64
        from fedml_tpu.agents import sign_job, _topic_upgrade
        _, master, slave = cluster
        pkg, _sha = self._package(registry)
        msg = {"request_id": "bad-digest", "version": "6.6.6",
               "sha256": "0" * 64,
               "package_b64": base64.b64encode(
                   open(pkg, "rb").read()).decode()}
        master.center.publish(_topic_upgrade(7), sign_job(msg))
        assert master.wait_for_status("bad-digest", {"FAILED"},
                                      timeout_s=20) == "FAILED"
        assert slave.current_version != "6.6.6"

    def test_unsigned_upgrade_refused(self, cluster, registry):
        from fedml_tpu.agents import _topic_upgrade
        _, master, slave = cluster
        master.center.publish(_topic_upgrade(7), {
            "request_id": "evil-up", "version": "9.9.9",
            "sha256": "x", "package_b64": ""})
        assert master.wait_for_status("evil-up", {"FAILED"},
                                      timeout_s=20) == "FAILED"
        assert slave.current_version is None \
            or slave.current_version != "9.9.9"

    def test_non_zip_package_resolves_failed(self, cluster, registry):
        """A digest-valid but unreadable package must still resolve the
        request id — the master is blocked on it."""
        notzip = registry / "notes.txt"
        notzip.write_text("not a zip")
        _, master, _ = cluster
        rid = master.dispatch_upgrade(7, str(notzip), version="4.0")
        assert master.wait_for_status(rid, {"FAILED"}, timeout_s=20) \
            == "FAILED"

    def test_path_choosing_version_refused(self, cluster, registry):
        """A signed payload must not choose the staging directory: the
        version string is an identifier, not a path."""
        pkg, _ = self._package(registry)
        _, master, _ = cluster
        rid = master.dispatch_upgrade(7, pkg, version="../../../tmp/evil")
        assert master.wait_for_status(rid, {"FAILED"}, timeout_s=20) \
            == "FAILED"
        assert not (registry / "tmp").exists()

    def test_presence_heartbeat_heals_late_master(self, registry):
        """A registry-wired master that starts AFTER the agent still
        learns of it via the presence heartbeat (no retained messages),
        and the proof on the wire is an HMAC — never the raw token."""
        from fedml_tpu.agents import MessageCenter
        from fedml_tpu.agents.accounts import AccountRegistry
        reg = AccountRegistry(str(registry / "acc5.db"))
        _, token = reg.register_device("k", device_id="21")
        broker = PubSubBroker()
        seen = []
        try:
            slave = SlaveAgent(device_id=21, broker_host="127.0.0.1",
                               broker_port=broker.port,
                               device_token=token)
            spy = MessageCenter("127.0.0.1", broker.port)
            spy.subscribe("fl_client/agent/online",
                          lambda p: seen.append(p))
            spy.start()
            slave.start(presence_interval_s=0.3)
            # master arrives late: first presence long gone
            time.sleep(0.5)
            master = MasterAgent("127.0.0.1", broker.port, registry=reg)
            master.start()
            assert master.wait_for_device(21, DEVICE_IDLE, timeout_s=10) \
                == DEVICE_IDLE
            # the credential itself never rides the topic
            assert seen and all(token not in str(p) for p in seen)
            assert all("proof" in p for p in seen)
            slave.stop()
            spy.stop()
            master.stop()
        finally:
            broker.stop()

    def test_traversal_package_refused(self, cluster, registry):
        import base64
        import hashlib
        import zipfile
        from fedml_tpu.agents import sign_job, _topic_upgrade
        _, master, _ = cluster
        pkg = registry / "evil.zip"
        with zipfile.ZipFile(pkg, "w") as z:
            z.writestr("../../escape.py", "boom")
        blob = pkg.read_bytes()
        msg = {"request_id": "trav", "version": "3.0.0",
               "sha256": hashlib.sha256(blob).hexdigest(),
               "package_b64": base64.b64encode(blob).decode()}
        master.center.publish(_topic_upgrade(7), sign_job(msg))
        assert master.wait_for_status("trav", {"FAILED"},
                                      timeout_s=20) == "FAILED"
        assert not (registry / "runs" / "agent_7" / "escape.py").exists()
        assert not (registry / "escape.py").exists()


class TestStatusMAC:
    """Job-status frames carry a device-credential HMAC (like presence
    proofs): a broker-authenticated peer WITHOUT the bind token must not
    be able to flip a bound device's live job to FAILED/FINISHED on a
    registry-wired master (round-5 advisor)."""

    def _wired(self, registry, db):
        from fedml_tpu.agents import MasterAgent, MessageCenter
        from fedml_tpu.agents.accounts import AccountRegistry
        reg = AccountRegistry(str(registry / db))
        did, token = reg.register_device("k", device_id="31")
        broker = PubSubBroker()
        master = MasterAgent("127.0.0.1", broker.port, registry=reg)
        master.start()
        spy = MessageCenter("127.0.0.1", broker.port)
        spy.start()
        return reg, did, token, broker, master, spy

    @staticmethod
    def _signed_status(token, did, rid, status):
        import uuid as _uuid
        from fedml_tpu.agents.accounts import status_proof
        ts = time.time()
        nonce = _uuid.uuid4().hex
        return {"device_id": int(did), "request_id": rid,
                "status": status, "ts": ts, "nonce": nonce,
                "proof": status_proof(token, did, rid, status, ts, nonce)}

    def test_forged_status_cannot_flip_bound_devices_job(self, registry):
        from fedml_tpu.agents import JOB_RUNNING
        reg, did, token, broker, master, spy = self._wired(registry,
                                                           "st1.db")
        try:
            # legitimate, proof-carrying RUNNING status lands
            spy.publish("fl_client/mlops/status",
                        self._signed_status(token, did, "job-1",
                                            JOB_RUNNING))
            assert master.wait_for_status("job-1", {JOB_RUNNING},
                                          timeout_s=10) == JOB_RUNNING
            # forged frames (no proof / wrong proof) must not mutate it
            spy.publish("fl_client/mlops/status", {
                "device_id": 31, "request_id": "job-1",
                "status": "FAILED"})
            forged = self._signed_status(token, did, "job-1", "FINISHED")
            forged["proof"] = "0" * 64
            spy.publish("fl_client/mlops/status", forged)
            time.sleep(0.8)
            assert master.job_status("job-1") == JOB_RUNNING
            assert master.devices[31]["status"] == "RUNNING"
        finally:
            spy.stop()
            master.stop()
            broker.stop()

    def test_replayed_status_nonce_rejected(self, registry):
        from fedml_tpu.agents import JOB_FINISHED, JOB_RUNNING
        reg, did, token, broker, master, spy = self._wired(registry,
                                                           "st2.db")
        try:
            running = self._signed_status(token, did, "job-2", JOB_RUNNING)
            spy.publish("fl_client/mlops/status", dict(running))
            assert master.wait_for_status("job-2", {JOB_RUNNING},
                                          timeout_s=10) == JOB_RUNNING
            spy.publish("fl_client/mlops/status",
                        self._signed_status(token, did, "job-2",
                                            JOB_FINISHED))
            assert master.wait_for_status("job-2", {JOB_FINISHED},
                                          timeout_s=10) == JOB_FINISHED
            # a harvested RUNNING frame replayed later must not resurrect
            spy.publish("fl_client/mlops/status", dict(running))
            time.sleep(0.8)
            assert master.job_status("job-2") == JOB_FINISHED
        finally:
            spy.stop()
            master.stop()
            broker.stop()

    def test_slave_attaches_status_proofs_end_to_end(self, registry):
        """A token-carrying slave's own statuses pass the MAC gate: the
        full dispatch->FAILED flow works through a registry-wired
        master (the job yaml is missing, so the slave reports FAILED —
        with a proof the master accepts)."""
        from fedml_tpu.agents import SlaveAgent, launch_job_remote
        reg, did, token, broker, master, spy = self._wired(registry,
                                                           "st3.db")
        slave = SlaveAgent(device_id=31, broker_host="127.0.0.1",
                           broker_port=broker.port, poll_s=0.1,
                           device_token=token)
        slave.start()
        try:
            assert master.wait_for_device(31, DEVICE_IDLE,
                                          timeout_s=10) == DEVICE_IDLE
            info = launch_job_remote(str(registry / "missing.yaml"),
                                     device_id=31, master=master,
                                     timeout_s=30)
            assert info["status"] == "FAILED"
        finally:
            slave.stop()
            spy.stop()
            master.stop()
            broker.stop()
