"""Client-slot batch folding (ISSUE 16 tentpole part 2).

``client_slot_fold: true`` folds the [S] schedule-slot axis into the
batch axis for optimizers whose aggregate is sample-additive at shared
params (FedSGD): one big-batch pass replaces the slot scan, so every
conv/matmul in the round sees an S-times-larger batch. Exactness is the
contract — parity with the scan path up to float summation order — and
configs that CANNOT fold (per-client trajectories, robust stack, DP,
per-slot selection metrics) must refuse loudly, not silently degrade.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core.algframe.types import TrainHyper


def sim_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                federated_optimizer="fedsgd", server_lr=0.5,
                client_num_in_total=8, client_num_per_round=8,
                comm_round=4, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=10_000, random_seed=5)
    base.update(kw)
    return Arguments(**base)


def build_sim(args):
    from fedml_tpu import data as data_mod, model as model_mod
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    return TPUSimulator(args, fed, bundle, create_optimizer(args, spec),
                        spec)


def hyper_for(args):
    return TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                      epochs=int(args.epochs))


def assert_params_close(a, b, rtol=1e-5, atol=1e-6):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=rtol, atol=atol)


class TestFoldParity:
    def test_fedsgd_round_parity(self):
        """Folded big-batch pass == slot scan, round for round."""
        scan = build_sim(sim_args())
        fold = build_sim(sim_args(client_slot_fold=True))
        assert not scan._slot_fold and fold._slot_fold
        hyper = hyper_for(sim_args())
        for r in range(3):
            scan.run_round(r, hyper)
            fold.run_round(r, hyper)
        assert_params_close(scan.params, fold.params)

    def test_fold_rides_fused_blocks_single_dispatch(self):
        """The folded core slots into the multi-round fused dispatch
        unchanged: one dispatch, same params as the scan-path block."""
        hyper = hyper_for(sim_args())
        scan = build_sim(sim_args())
        fold = build_sim(sim_args(client_slot_fold=True))
        scan.run_rounds_fused(0, 4, hyper)
        fold.run_rounds_fused(0, 4, hyper)
        assert fold.dispatch_stats["dispatches"] == 1
        assert_params_close(scan.params, fold.params)

    def test_fold_parity_under_chaos_dropout(self):
        """Slot masking becomes sample masking: a dropped client's rows
        zero out of the folded sums exactly as the scan's report gate
        zeroed its slot — chaos runs must stay in parity too."""
        kw = dict(chaos_dropout_prob=0.3, chaos_seed=11, comm_round=3)
        scan = build_sim(sim_args(**kw))
        fold = build_sim(sim_args(client_slot_fold=True, **kw))
        hyper = hyper_for(sim_args(**kw))
        for r in range(3):
            scan.run_round(r, hyper)
            fold.run_round(r, hyper)
        assert_params_close(scan.params, fold.params)

    def test_fold_parity_with_partial_participation(self):
        """Subsampled cohorts exercise the inactive padding slots of the
        canonical schedule width — they must vanish from the folded sums."""
        kw = dict(client_num_in_total=16, client_num_per_round=8)
        scan = build_sim(sim_args(**kw))
        fold = build_sim(sim_args(client_slot_fold=True, **kw))
        hyper = hyper_for(sim_args(**kw))
        scan.run_rounds_fused(0, 4, hyper)
        fold.run_rounds_fused(0, 4, hyper)
        assert_params_close(scan.params, fold.params)

    def test_fold_compiles_once(self, xla_compile_counter):
        args = sim_args(client_slot_fold=True, comm_round=12)
        sim = build_sim(args)
        hyper = hyper_for(args)
        sim.run_rounds_fused(0, 4, hyper)
        xla_compile_counter.reset()
        sim.run_rounds_fused(4, 4, hyper)
        sim.run_rounds_fused(8, 4, hyper)
        assert xla_compile_counter.delta() == 0


class TestFoldRefusals:
    """Loud refusal, not silent fallback: the measured mode must be the
    requested mode."""

    def test_off_strings_stay_off(self):
        for knob in (False, "false", "0"):
            sim = build_sim(sim_args(client_slot_fold=knob))
            assert not sim._slot_fold

    def test_refuses_per_client_trajectory_optimizer(self):
        """FedAvg runs local SGD trajectories — folding would change the
        algorithm, not just the layout."""
        with pytest.raises(ValueError, match="client_slot_fold"):
            build_sim(sim_args(federated_optimizer="fedavg",
                               client_slot_fold=True))

    def test_refuses_robust_mode(self):
        with pytest.raises(ValueError, match="robust"):
            build_sim(sim_args(client_slot_fold=True, enable_defense=True,
                               defense_type="rfa"))

    def test_refuses_local_dp(self):
        with pytest.raises(ValueError, match="DP"):
            build_sim(sim_args(client_slot_fold=True, enable_dp=True,
                               dp_type="local_dp", dp_epsilon=8.0))

    def test_refuses_tracking_selection(self):
        """Reputation-style selection consumes per-slot metrics, which a
        folded pass cannot produce."""
        with pytest.raises(ValueError, match="selection"):
            build_sim(sim_args(client_slot_fold=True,
                               client_num_in_total=16,
                               client_num_per_round=8,
                               client_selection="oort"))

    def test_refusal_lists_every_reason(self):
        """A multi-way-unfoldable config names ALL its blockers in one
        error, so the user fixes the config once."""
        with pytest.raises(ValueError) as ei:
            build_sim(sim_args(federated_optimizer="fedavg",
                               client_slot_fold=True, enable_defense=True,
                               defense_type="rfa"))
        msg = str(ei.value)
        assert "folds_client_slots" in msg and "robust" in msg
