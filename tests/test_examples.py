"""Every shipped example config must parse and dispatch to a real runner
(the heavy ones aren't trained here — config validity + runner wiring is
the contract; the digits example IS run end-to-end)."""

import glob
import os

import pytest

import fedml_tpu
from fedml_tpu.arguments import load_arguments

EXAMPLES = sorted(glob.glob(
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "examples", "**", "fedml_config.yaml"), recursive=True))


def test_examples_exist():
    assert len(EXAMPLES) >= 10


@pytest.mark.parametrize("path", EXAMPLES, ids=lambda p: "/".join(
    p.split(os.sep)[-3:-1]))
def test_example_config_parses_and_dispatches(path):
    args = load_arguments(path)
    assert args.training_type in ("simulation", "cross_silo", "cross_cloud",
                                  "cross_device", "fedml_serving")
    # simulation configs must resolve their model (heavy data not loaded)
    if args.training_type == "simulation" and args.model != "causal_lm":
        from fedml_tpu.model import create
        create(args, 10)


def test_digits_example_end_to_end(tmp_path):
    path = [p for p in EXAMPLES if "digits" in p][0]
    args = load_arguments(path)
    args.comm_round = 8
    args.data_cache_dir = str(tmp_path)
    r = fedml_tpu.run_simulation(backend="tpu", args=args)
    assert r["final_test_acc"] > 0.7, r["history"]
