"""Every shipped example EXECUTES at least one training round (VERDICT
r4 item 9 — parse-only checks let a yaml whose workload breaks at round 1
pass the gate). Heavy knobs are shrunk (1 round, few clients, synthetic
stand-ins allowed) but each example runs through its real runner path:
simulation examples through ``run_simulation``/``run_federated_llm``,
cross-silo and serving through the Message FSM over the in-proc broker,
cross-device through the device session (native engine included).
Reference counterpart: ``tests/test_federate/test_federate.sh``."""

import copy
import glob
import json
import os
import urllib.request

import pytest

import fedml_tpu
from fedml_tpu.arguments import load_arguments

pytestmark = pytest.mark.slow

EXAMPLES = sorted(glob.glob(
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "examples", "**", "fedml_config.yaml"), recursive=True))


def test_examples_exist():
    assert len(EXAMPLES) >= 10


def _shrink(args, tmp_path):
    """Tiny-run overrides: the contract is 'the config's workload trains',
    not 'it converges'."""
    args.comm_round = 1
    args.epochs = 1
    args.client_num_in_total = min(int(args.client_num_in_total), 4)
    args.client_num_per_round = min(int(args.client_num_per_round),
                                    int(args.client_num_in_total))
    args.frequency_of_the_test = 1
    args.allow_synthetic = True
    # tiny: on the 8-device virtual CPU mesh, a heavy per-device workload
    # (resnet18) with padded idle devices can trip XLA:CPU's 40 s
    # collective-rendezvous termination timeout
    args.synthetic_size = 64
    args.max_total_samples = 64  # the synthetic fallback floors at 4000
    args.synthetic_test_size = 64
    args.batch_size = min(int(args.batch_size), 8)
    args.data_cache_dir = str(tmp_path)
    return args


def _run_simulation_example(args):
    if str(args.model) == "causal_lm":
        from fedml_tpu.llm.federated import run_federated_llm
        args.llm_hidden_size = 32
        args.llm_num_layers = 1
        args.llm_num_heads = 2
        args.llm_intermediate_size = 64
        args.llm_max_seq_len = 64
        return run_federated_llm(args)
    backend = str(getattr(args, "backend", "tpu")).lower()
    backend = backend if backend in ("sp", "tpu") else "tpu"
    return fedml_tpu.run_simulation(backend=backend, args=args)


def _run_cross_silo_example(args):
    """Server + silo clients as threads over the in-proc broker, through
    the SAME CrossSiloRunner dispatch a per-process deployment uses (so
    SecAgg/LSA examples exercise their full message FSMs)."""
    from fedml_tpu.cross_silo import run_inproc_session
    from fedml_tpu.cross_silo.horizontal.runner import CrossSiloRunner
    from fedml_tpu import data as data_mod
    from fedml_tpu import model as model_mod
    args.backend = "INPROC"
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    n = int(args.client_num_per_round)

    def build():
        managers = []
        for role, rank in [("server", 0)] + [("client", r)
                                             for r in range(1, n + 1)]:
            a = copy.copy(args)
            a.role, a.rank = role, rank
            managers.append(CrossSiloRunner(a, fed, bundle).manager)
        return managers

    return run_inproc_session(args, build)


def _run_cross_device_example(args):
    from fedml_tpu.cross_device.runner import run_cross_device_inproc
    from fedml_tpu import data as data_mod
    from fedml_tpu import model as model_mod
    args.backend = "INPROC"
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    engines = None
    if str(getattr(args, "device_engine", "")) == "native":
        engines = ["native"] + [None] * (int(args.client_num_per_round) - 1)
    return run_cross_device_inproc(args, fed, bundle, engines=engines)


def _run_serving_example(args):
    from fedml_tpu.cross_silo import run_inproc_session
    from fedml_tpu.cross_silo.horizontal.runner import CrossSiloRunner
    from fedml_tpu.runner import FedMLRunner
    from fedml_tpu import data as data_mod
    from fedml_tpu import model as model_mod
    args.backend = "INPROC"
    args.serving_block = False  # the gate must not block on a live server
    args.serving_port = 0
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    n = int(args.client_num_per_round)
    box = {}

    def build():
        sa = copy.copy(args)
        sa.role, sa.rank = "server", 0
        server = FedMLRunner(sa, dataset=fed, model=bundle).runner

        class ServerShim:  # capture the serving runner's return value
            def run(self):
                box["result"] = server.run()

        clients = []
        for r in range(1, n + 1):
            a = copy.copy(args)
            a.role, a.rank = "client", r
            clients.append(CrossSiloRunner(a, fed, bundle).manager)
        return [ServerShim()] + clients

    run_inproc_session(args, build)
    result = box.get("result")
    assert result and result.get("serving_port")
    # the endpoint is LIVE: round-trip a prediction on one test example
    import numpy as np
    sample = [np.asarray(fed.test["x"][0, 0], np.float32).reshape(-1)
              .tolist()]
    req = urllib.request.Request(
        f"http://127.0.0.1:{result['serving_port']}/predict",
        data=json.dumps({"inputs": sample}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        out = json.load(r)
    assert "classes" in out
    return result


@pytest.mark.parametrize("path", EXAMPLES, ids=lambda p: "/".join(
    p.split(os.sep)[-3:-1]))
def test_example_trains_one_round(path, tmp_path):
    args = _shrink(load_arguments(path), tmp_path)
    ttype = str(args.training_type)
    if ttype == "simulation":
        result = _run_simulation_example(args)
    elif ttype in ("cross_silo", "cross_cloud"):
        result = _run_cross_silo_example(args)
    elif ttype == "cross_device":
        result = _run_cross_device_example(args)
    elif ttype == "fedml_serving":
        result = _run_serving_example(args)
    else:
        pytest.fail(f"unknown training_type {ttype!r}")
    assert isinstance(result, dict), result
    hist = result.get("history")
    assert hist, f"{path} trained no rounds: {result}"
    acc = result.get("final_test_acc")
    assert acc is None or 0.0 <= acc <= 1.0


def test_digits_example_end_to_end(tmp_path):
    """The digits example keeps its stronger contract: real data, 8
    rounds, real accuracy."""
    path = [p for p in EXAMPLES if "digits" in p][0]
    args = load_arguments(path)
    args.comm_round = 8
    args.data_cache_dir = str(tmp_path)
    r = fedml_tpu.run_simulation(backend="tpu", args=args)
    assert r["final_test_acc"] > 0.7, r["history"]
