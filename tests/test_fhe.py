"""FHE aggregation (reference core/fhe/fhe_agg.py): Paillier-backed
encrypted FedAvg must equal the plaintext weighted average."""

import numpy as np

from fedml_tpu.core.fhe import FedMLFHE, fhe_fedavg, keygen
from fedml_tpu.core.fhe.paillier import (add_ciphertexts, pack_vector,
                                         unpack_vector)


def test_paillier_roundtrip_and_homomorphism():
    pub, priv = keygen(512)
    a, b = 123456789, 987654321
    ca, cb = pub.encrypt_int(a), pub.encrypt_int(b)
    assert priv.decrypt_int(ca) == a
    assert priv.decrypt_int(pub.add(ca, cb)) == a + b
    # semantic security: same plaintext, different ciphertexts
    assert pub.encrypt_int(a) != ca


def test_packed_vector_sum():
    pub, priv = keygen(512)
    rs = np.random.RandomState(0)
    v1 = rs.randn(300).astype(np.float64)
    v2 = rs.randn(300).astype(np.float64)
    c1 = pack_vector(v1, pub)
    c2 = pack_vector(v2, pub)
    agg = add_ciphertexts([c1, c2], pub)
    out = unpack_vector(agg, priv, 300, n_added=2)
    np.testing.assert_allclose(out, v1 + v2, atol=1e-4)


def test_fhe_fedavg_matches_plain():
    pub, priv = keygen(512)
    rs = np.random.RandomState(1)
    vecs = [rs.randn(200) for _ in range(4)]
    weights = [10.0, 20.0, 30.0, 40.0]
    enc_avg = fhe_fedavg(vecs, weights, pub, priv)
    total = sum(weights)
    plain = sum(v * (w / total) for v, w in zip(vecs, weights))
    np.testing.assert_allclose(enc_avg, plain, atol=1e-4)


def test_facade_flags():
    class A:
        enable_fhe = True
        fhe_key_bits = 256
    f = FedMLFHE(A())
    assert f.is_fhe_enabled()
    v = np.array([0.5, -1.25, 3.0])
    cts = f.fhe_enc(v)
    np.testing.assert_allclose(f.fhe_dec(cts, 3), v, atol=1e-4)
