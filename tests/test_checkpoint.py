"""Checkpoint/resume: a run interrupted at round k and resumed must end with
the exact params of an uninterrupted run (determinism makes this testable)."""

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments


def make_args(tmp, **kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=8, client_num_per_round=8,
                comm_round=4, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=2, random_seed=11,
                checkpoint_dir=str(tmp), checkpoint_every_rounds=2)
    base.update(kw)
    return Arguments(**base)


@pytest.mark.parametrize("backend", ["sp", "tpu"])
def test_resume_matches_uninterrupted(tmp_path, backend):
    full_dir = tmp_path / "full"
    part_dir = tmp_path / "part"
    # uninterrupted 4-round run
    r_full = fedml_tpu.run_simulation(backend=backend,
                                      args=make_args(full_dir))
    # interrupted: run only 2 rounds (checkpoint lands at round 1)...
    fedml_tpu.run_simulation(backend=backend,
                             args=make_args(part_dir, comm_round=2))
    # ...then resume to 4 — restores round-1 state and continues
    r_resumed = fedml_tpu.run_simulation(backend=backend,
                                         args=make_args(part_dir))
    for a, b in zip(jax.tree_util.tree_leaves(r_full["params"]),
                    jax.tree_util.tree_leaves(r_resumed["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_stateful_optimizer_checkpoint(tmp_path):
    """SCAFFOLD's per-client control variates must survive the round trip."""
    args = make_args(tmp_path, federated_optimizer="SCAFFOLD",
                     learning_rate=0.05)
    r_full = fedml_tpu.run_simulation(backend="tpu", args=args)
    args2 = make_args(tmp_path / "p", federated_optimizer="SCAFFOLD",
                      learning_rate=0.05, comm_round=2)
    fedml_tpu.run_simulation(backend="tpu", args=args2)
    args3 = make_args(tmp_path / "p", federated_optimizer="SCAFFOLD",
                      learning_rate=0.05)
    r_res = fedml_tpu.run_simulation(backend="tpu", args=args3)
    for a, b in zip(jax.tree_util.tree_leaves(r_full["params"]),
                    jax.tree_util.tree_leaves(r_res["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_resume_parity_through_fused_blocks(tmp_path):
    """With a large eval interval the TPU engine runs multi-round FUSED
    dispatch blocks; checkpoint rounds must end a block so the saved state
    matches its round label (a mid-block save would store end-of-block
    params under an earlier round and corrupt the resumed trajectory)."""
    kw = dict(frequency_of_the_test=100, checkpoint_every_rounds=3,
              comm_round=8)
    full_dir = tmp_path / "full"
    part_dir = tmp_path / "part"
    r_full = fedml_tpu.run_simulation(backend="tpu",
                                      args=make_args(full_dir, **kw))
    # interrupted after 4 rounds: the round-2 checkpoint is the restore
    # point, taken at a fused-block boundary
    fedml_tpu.run_simulation(backend="tpu",
                             args=make_args(part_dir, **{**kw,
                                                         "comm_round": 4}))
    r_resumed = fedml_tpu.run_simulation(backend="tpu",
                                         args=make_args(part_dir, **kw))
    for a, b in zip(jax.tree_util.tree_leaves(r_full["params"]),
                    jax.tree_util.tree_leaves(r_resumed["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
