"""Worker for the multi-process LLM FSDP/TP test: one HOST of a
two-process slice. The global mesh is {fsdp: 4, tensor: 2} over 8 devices
spanning both processes — the exact sharded train step a multi-host TPU
pod runs for FedLLM fine-tuning. Rank 0 writes the post-step loss and a
param checksum for the pytest process to compare against the
single-process run."""

import json
import os
import sys


def main() -> None:
    out_path = sys.argv[1]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from fedml_tpu.cross_silo.hierarchical.process_group import (
        init_silo_process_group)
    assert init_silo_process_group()
    assert len(jax.devices()) == 8

    loss, checksum = _llm_fsdp_step()

    if jax.process_index() == 0:
        with open(out_path, "w") as f:
            json.dump({"loss": loss, "checksum": checksum,
                       "n_processes": jax.process_count()}, f)
    jax.distributed.shutdown()


def _llm_fsdp_step():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from fedml_tpu.core.mesh import build_mesh
    from fedml_tpu.llm import CausalLMTrainer, LLMConfig, init_llm
    from fedml_tpu.llm.sharding import (llm_param_specs,
                                        make_sharded_train_step,
                                        shard_llm_params)

    mesh = build_mesh({"data": 1, "fsdp": 4, "tensor": 2},
                      devices=jax.devices())
    cfg = LLMConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                    num_layers=2, num_heads=4, max_seq_len=16,
                    tie_embeddings=False)
    model, params = init_llm(cfg, jax.random.PRNGKey(0))
    spec = CausalLMTrainer(
        lambda p, x, rng=None, train=False: model.apply({"params": p}, x))
    x = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 4, 64)
    batch = {"x": x, "y": x, "mask": jnp.ones(8)}
    opt = optax.sgd(0.1)
    specs = llm_param_specs(params, mesh)
    with mesh:
        sharded = shard_llm_params(params, mesh)
        step = make_sharded_train_step(
            lambda p, b, r: spec.loss(p, b, r), opt, mesh, specs)
        new_params, _, loss = step(sharded, opt.init(sharded), batch,
                                   jax.random.PRNGKey(0))
    # checksum over the (replicable) gathered params: sum of abs sums
    total = 0.0
    for leaf in jax.tree_util.tree_leaves(new_params):
        total += float(jnp.abs(leaf.astype(jnp.float32)).sum())
    return float(loss), total


if __name__ == "__main__":
    main()
