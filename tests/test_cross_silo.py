"""Cross-silo WAN runtime: full Message-FSM FL session (server + N silo
clients in threads), learning + parity against the golden SP loop on the
same per-silo data."""

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu import data as data_mod
from fedml_tpu import model as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_silo.horizontal.runner import run_cross_silo_inproc

pytestmark = __import__('pytest').mark.slow


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=4, client_num_per_round=4,
                comm_round=4, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=1, random_seed=9,
                training_type="cross_silo")
    base.update(kw)
    return Arguments(**base)


def test_inproc_session_learns():
    args = make_args()
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    result = run_cross_silo_inproc(args, fed, bundle)
    assert result is not None
    assert result["final_test_acc"] > 0.6, result["history"]
    assert len(result["history"]) == 4


def test_round_timeout_with_dead_silo():
    """A silo that never comes up must not stall the round forever: the
    server aggregates the silos that did report once the timeout fires
    (capability the reference lacks, SURVEY §5.3)."""
    import threading
    from fedml_tpu.core.distributed.communication.inproc import InProcBroker
    from fedml_tpu.cross_silo.horizontal.runner import (build_client,
                                                        build_server)

    # round_timeout_s must exceed the per-client jit-compile skew (threads
    # compile concurrently but finish tens of seconds apart on CPU)
    args = make_args(comm_round=2, round_timeout_s=20.0)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    broker = InProcBroker()
    args.inproc_broker = broker
    server = build_server(args, fed, bundle, backend="INPROC")
    # only 3 of the 4 expected silos start; the server's online handshake
    # expects client_num_per_round, so mark expectation accordingly
    server.client_num = 3
    server.aggregator.client_num = 4  # 4 expected models -> timeout path
    clients = [build_client(args, fed, bundle, rank=r, backend="INPROC")
               for r in (1, 2, 3)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()

    done = {}

    def run_server():
        server.run()
        done["ok"] = True

    st = threading.Thread(target=run_server, daemon=True)
    st.start()
    st.join(timeout=180.0)
    assert done.get("ok"), "server stalled on a dead silo"
    assert server.result is not None and len(server.result["history"]) == 2


def test_cross_silo_matches_sp_golden():
    """Same data, full participation, plain SGD: the WAN FSM must produce
    the same global model as the SP golden loop (weighted averaging of
    locally-trained full models == averaging of deltas when all start from
    the same params)."""
    kw = dict(comm_round=2)
    args = make_args(**kw)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    result = run_cross_silo_inproc(args, fed, bundle)

    sim_args = make_args(**kw)
    sim_args.training_type = "simulation"
    r_sp = fedml_tpu.run_simulation(backend="sp", args=sim_args)
    for a, b in zip(jax.tree_util.tree_leaves(r_sp["params"]),
                    jax.tree_util.tree_leaves(result["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_cross_silo_session_over_real_grpc():
    """Full FL session over the real gRPC transport (not in-proc): server +
    2 silo clients, each with its own gRPC server on loopback — the wire
    path a multi-host deployment uses."""
    import threading
    from fedml_tpu.cross_silo.horizontal.runner import (build_client,
                                                        build_server)
    args = make_args(client_num_in_total=2, client_num_per_round=2,
                     comm_round=2, grpc_base_port=39990)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    server = build_server(args, fed, bundle, backend="GRPC")
    clients = [build_client(args, fed, bundle, rank=r, backend="GRPC")
               for r in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    done = {}

    def run_server():
        server.run()
        done["ok"] = True

    st = threading.Thread(target=run_server, daemon=True)
    st.start()
    st.join(timeout=240)
    assert done.get("ok"), "gRPC session did not complete"
    assert len(server.result["history"]) == 2
    assert server.result["final_test_acc"] > 0.6
