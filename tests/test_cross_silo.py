"""Cross-silo WAN runtime: full Message-FSM FL session (server + N silo
clients in threads), learning + parity against the golden SP loop on the
same per-silo data."""

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu import data as data_mod
from fedml_tpu import model as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_silo.horizontal.runner import run_cross_silo_inproc

pytestmark = __import__('pytest').mark.slow


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=4, client_num_per_round=4,
                comm_round=4, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=1, random_seed=9,
                training_type="cross_silo")
    base.update(kw)
    return Arguments(**base)


def test_inproc_session_learns():
    args = make_args()
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    result = run_cross_silo_inproc(args, fed, bundle)
    assert result is not None
    assert result["final_test_acc"] > 0.6, result["history"]
    assert len(result["history"]) == 4


def test_round_timeout_with_dead_silo():
    """A silo that never comes up must not stall the round forever: the
    server aggregates the silos that did report once the timeout fires
    (capability the reference lacks, SURVEY §5.3)."""
    import threading
    from fedml_tpu.core.distributed.communication.inproc import InProcBroker
    from fedml_tpu.cross_silo.horizontal.runner import (build_client,
                                                        build_server)

    # round_timeout_s must exceed the per-client jit-compile skew (threads
    # compile concurrently but finish tens of seconds apart on CPU)
    args = make_args(comm_round=2, round_timeout_s=20.0)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    broker = InProcBroker()
    args.inproc_broker = broker
    server = build_server(args, fed, bundle, backend="INPROC")
    # only 3 of the 4 expected silos start; the server's online handshake
    # expects client_num_per_round, so mark expectation accordingly
    server.client_num = 3
    server.aggregator.client_num = 4  # 4 expected models -> timeout path
    clients = [build_client(args, fed, bundle, rank=r, backend="INPROC")
               for r in (1, 2, 3)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()

    done = {}

    def run_server():
        server.run()
        done["ok"] = True

    st = threading.Thread(target=run_server, daemon=True)
    st.start()
    st.join(timeout=180.0)
    assert done.get("ok"), "server stalled on a dead silo"
    assert server.result is not None and len(server.result["history"]) == 2


def test_cross_silo_matches_sp_golden():
    """Same data, full participation, plain SGD: the WAN FSM must produce
    the same global model as the SP golden loop (weighted averaging of
    locally-trained full models == averaging of deltas when all start from
    the same params)."""
    kw = dict(comm_round=2)
    args = make_args(**kw)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    result = run_cross_silo_inproc(args, fed, bundle)

    sim_args = make_args(**kw)
    sim_args.training_type = "simulation"
    r_sp = fedml_tpu.run_simulation(backend="sp", args=sim_args)
    for a, b in zip(jax.tree_util.tree_leaves(r_sp["params"]),
                    jax.tree_util.tree_leaves(result["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.chaos
def test_chaos_dropout_session_completes():
    """Seeded 25% silo dropout + stragglers over the in-proc WAN FSM: the
    round timeout + quorum tolerance must carry the session through every
    round, and the server's fault ledger must reconcile injected dropouts
    with the silos it observed reporting."""
    import threading
    from fedml_tpu.core.chaos import FaultPlan
    from fedml_tpu.core.distributed.communication.inproc import InProcBroker
    from fedml_tpu.cross_silo.horizontal.runner import (build_client,
                                                        build_server)

    # round_timeout_s must exceed the per-client jit-compile skew (see
    # test_round_timeout_with_dead_silo)
    args = make_args(comm_round=3, round_timeout_s=20.0,
                     chaos_dropout_prob=0.25, chaos_straggler_prob=0.2,
                     chaos_seed=23)
    plan = FaultPlan.from_args(args)
    ranks = [1, 2, 3, 4]
    # the seed must actually schedule at least one dropout in-session
    assert any(plan.is_dropped(r, rank) for r in range(3) for rank in ranks)
    broker = InProcBroker()
    args.inproc_broker = broker
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    server = build_server(args, fed, bundle, backend="INPROC")
    clients = [build_client(args, fed, bundle, rank=r, backend="INPROC")
               for r in ranks]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    done = {}

    def run_server():
        server.run()
        done["ok"] = True

    st = threading.Thread(target=run_server, daemon=True)
    st.start()
    st.join(timeout=240.0)
    assert done.get("ok"), "chaos session stalled"
    assert len(server.result["history"]) == 3
    recs = server.chaos_ledger.rounds()
    assert len(recs) == 3
    for rec in recs:
        observed, injected = rec["observed"], rec["injected"]
        assert 1 <= observed["reported"] <= observed["expected"]
        if injected["dropped"]:
            # every injected dropout is a silo the server did NOT hear from
            assert observed["reported"] < observed["expected"]


@pytest.mark.chaos
def test_chaos_round_with_zero_uploads_is_skipped_not_stalled():
    """Seed 1 drops BOTH silos in round 1: no upload ever arrives for that
    round, so the broadcast-armed timeout (+ one grace interval) must fire
    and the server must SKIP the round — advancing with the global model
    unchanged — instead of stalling forever on an upload-armed timer that
    never starts."""
    import threading
    from fedml_tpu.core.chaos import FaultPlan
    from fedml_tpu.core.distributed.communication.inproc import InProcBroker
    from fedml_tpu.cross_silo.horizontal.runner import (build_client,
                                                        build_server)

    args = make_args(client_num_in_total=2, client_num_per_round=2,
                     comm_round=3, round_timeout_s=12.0,
                     chaos_dropout_prob=0.5, chaos_seed=1)
    plan = FaultPlan.from_args(args)
    assert all(plan.is_dropped(1, rk) for rk in (1, 2))  # the dead round
    assert not any(plan.is_dropped(0, rk) for rk in (1, 2))
    broker = InProcBroker()
    args.inproc_broker = broker
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    server = build_server(args, fed, bundle, backend="INPROC")
    clients = [build_client(args, fed, bundle, rank=r, backend="INPROC")
               for r in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    done = {}

    def run_server():
        server.run()
        done["ok"] = True

    st = threading.Thread(target=run_server, daemon=True)
    st.start()
    st.join(timeout=240.0)
    assert done.get("ok"), "server stalled on the zero-upload round"
    skipped = [r for r in server.chaos_ledger.rounds()
               if r["observed"].get("skipped")]
    assert skipped and skipped[0]["round_idx"] == 1
    # rounds 0 and 2 aggregated normally; round 1 was skipped
    assert [h["round"] for h in server.result["history"]] == [0, 2]


@pytest.mark.chaos
def test_chaos_link_faults_session_completes():
    """Seeded link loss + duplication + delay at the Message send seam:
    the ONLINE re-announce handshake, round timeout, duplicate-upload
    idempotency, and stale-round tagging must together carry the session
    through every round."""
    import threading
    from fedml_tpu.core.chaos import ChaosCommManager
    from fedml_tpu.core.distributed.communication.inproc import InProcBroker
    from fedml_tpu.cross_silo.horizontal.runner import (build_client,
                                                        build_server)

    args = make_args(comm_round=3, round_timeout_s=20.0,
                     chaos_link_loss_prob=0.08, chaos_link_dup_prob=0.1,
                     chaos_link_delay_prob=0.1, chaos_link_delay_s=0.2,
                     chaos_seed=31)
    broker = InProcBroker()
    args.inproc_broker = broker
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    server = build_server(args, fed, bundle, backend="INPROC")
    assert isinstance(server.com_manager, ChaosCommManager)
    clients = [build_client(args, fed, bundle, rank=r, backend="INPROC")
               for r in (1, 2, 3, 4)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    done = {}

    def run_server():
        server.run()
        done["ok"] = True

    st = threading.Thread(target=run_server, daemon=True)
    st.start()
    st.join(timeout=240.0)
    assert done.get("ok"), "link-fault session stalled"
    assert len(server.result["history"]) == 3
    assert server.result["final_test_acc"] > 0.5
    # the interceptor actually fired somewhere in the session
    fault_events = list(server.com_manager.ledger.links())
    for c in clients:
        if isinstance(c.com_manager, ChaosCommManager):
            fault_events.extend(c.com_manager.ledger.links())
    assert fault_events


def test_cross_silo_session_over_real_grpc():
    """Full FL session over the real gRPC transport (not in-proc): server +
    2 silo clients, each with its own gRPC server on loopback — the wire
    path a multi-host deployment uses."""
    import threading
    from fedml_tpu.cross_silo.horizontal.runner import (build_client,
                                                        build_server)
    args = make_args(client_num_in_total=2, client_num_per_round=2,
                     comm_round=2, grpc_base_port=39990)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    server = build_server(args, fed, bundle, backend="GRPC")
    clients = [build_client(args, fed, bundle, rank=r, backend="GRPC")
               for r in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    done = {}

    def run_server():
        server.run()
        done["ok"] = True

    st = threading.Thread(target=run_server, daemon=True)
    st.start()
    st.join(timeout=240)
    assert done.get("ok"), "gRPC session did not complete"
    assert len(server.result["history"]) == 2
    assert server.result["final_test_acc"] > 0.6
