"""Byzantine-robust buffered-async rounds (ISSUE 7).

Covers the defended-pour tentpole: staleness-0 bit-identity of a defended
pour vs the sync sharded defense (the parity anchor), compile-once under
defended pours (stateless AND stateful defenses), byzantine updates kept
out of the model (params parity vs the attack-free defended run),
foolsgold crash-resume verdict replay through the async checkpoint (base
ring + defense state), the partial-pour row-mask kernels, defended pours
on the cross-silo async aggregator (re-base at the base ring, verdict ->
silo reputation -> benching), the adaptive ``rfa_tol`` Weiszfeld early
exit, the ``silo_index_assignment`` satellite, async-aware dispatch
(reputation benching out of the arrival rotation; oort/power_of_choice
ranking), and the loud refusals that remain. The 200-pour byzantine
chaos soak is slow-marked.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.constants import AXIS_CLIENT
from fedml_tpu.core.async_rounds import pour_weights

pytestmark = pytest.mark.async_rounds


def sim_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=8, client_num_per_round=8,
                comm_round=8, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=0, random_seed=3,
                round_mode="async_buffered")
    base.update(kw)
    return Arguments(**base)


def build_async_sim(args):
    from fedml_tpu import data as data_mod, model as model_mod
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.async_engine import AsyncBufferedSimulator

    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    return AsyncBufferedSimulator(args, fed, bundle,
                                  create_optimizer(args, spec), spec)


def hyper_for(args):
    from fedml_tpu.core.algframe.types import TrainHyper
    return TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                      epochs=int(args.epochs))


def leaves_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(x)),
                                      np.asarray(jax.device_get(y)))


# --- the parity anchor: staleness 0 == the sync defended round ---------------

class TestDefendedPourParity:
    @pytest.mark.parametrize("defense,extra", [
        ("krum", dict(byzantine_client_num=1)),
        ("median", {}),
        ("foolsgold", {}),
    ])
    def test_staleness0_pour_bit_identical_to_sync_defense(self, defense,
                                                           extra):
        """K = concurrency with constant weighting and alpha 1: the first
        real pour aggregates a full staleness-0 cohort with merge scale
        exactly 1.0 — its params step must be BIT-identical to the sync
        sharded defense run on the same rows/weights/keys (which the
        robust_fused suite pins against the host kernels)."""
        from fedml_tpu.core.collectives import vector_to_tree_like
        from fedml_tpu.core.security.defense import sharded
        from fedml_tpu.simulation.tpu.engine import DEFENSE_FOLD

        args = sim_args(async_buffer_k=8, async_alpha=1.0,
                        async_staleness_weighting="constant",
                        enable_defense=True, defense_type=defense, **extra)
        sim = build_async_sim(args)
        hyper = hyper_for(args)
        sim._bootstrap(hyper)
        sim._absorb_until(sim.k)
        entries = list(sim.buffer._entries)
        assert len(entries) == sim.k
        assert all(e.version == 0 for e in entries)  # staleness 0
        mat = np.stack([np.asarray(jax.device_get(e.update),
                                   np.float32)[:sim._true_d]
                        for e in entries])
        w = np.asarray([e.weight for e in entries], np.float64)
        norm_w, merge_scale = pour_weights(w, np.zeros(len(entries)),
                                           sim._staleness_fn(),
                                           sim.merge_alpha)
        assert merge_scale == 1.0
        params_before = jax.device_get(sim.params)
        sim._pour_step(hyper)
        key = jax.random.fold_in(
            jax.random.fold_in(sim.rng, sim._dispatch_seq), DEFENSE_FOLD)
        out = sharded.defend_matrix_sharded(
            sim.mesh, AXIS_CLIENT, jnp.asarray(mat),
            jnp.asarray(norm_w, jnp.float32), defense,
            hp=sharded.DefenseHP.from_defender(sim.defender),
            ids=np.asarray([e.client_id for e in entries], np.int32),
            defense_key=key,
            row_mask=np.ones(len(entries), np.float32))
        vec = out[0] if isinstance(out, tuple) else out
        expected = jax.tree_util.tree_map(
            lambda p, d: np.asarray(p) + np.asarray(jax.device_get(d)),
            params_before, vector_to_tree_like(vec, params_before))
        leaves_equal(expected, sim.params)

    def test_rebase_corrects_stale_rows(self):
        """A buffered update from version v-s must reach the defense
        re-based by the server movement it missed: feed the ring a known
        movement and check the defended pour applies the corrected
        median, not the raw one."""
        args = sim_args(async_buffer_k=4, async_alpha=1.0,
                        async_staleness_weighting="constant",
                        enable_defense=True, defense_type="median")
        sim = build_async_sim(args)
        hyper = hyper_for(args)
        sim._bootstrap(hyper)
        # two pours so the ring holds real movement and staleness exists
        sim._pour_step(hyper)
        sim._pour_step(hyper)
        assert sim.version >= 2
        ring = np.asarray(jax.device_get(sim._ring))
        assert float(np.max(np.abs(ring))) > 0.0  # movement recorded
        # at least one later pour must have seen genuine staleness
        pours = sim.chaos_ledger.pours()
        stal = [a["staleness"] for p in pours
                for a in p["injected"]["arrivals"]]
        assert max(stal) >= 1

    def test_defended_pour_compiles_exactly_once(self, xla_compile_counter):
        args = sim_args(enable_defense=True, defense_type="krum",
                        byzantine_client_num=1, enable_attack=True,
                        attack_type="byzantine_flip", attack_scale=2.0)
        sim = build_async_sim(args)
        hyper = hyper_for(args)
        sim._bootstrap(hyper)
        for _ in range(3):
            sim._pour_step(hyper)
        assert sim.dispatch_stats["compiles"] == 1
        xla_compile_counter.reset()
        for _ in range(5):
            sim._pour_step(hyper)
        assert xla_compile_counter.delta() == 0
        assert sim.dispatch_stats["compiles"] == 1

    def test_stateful_defended_pour_compiles_exactly_once(
            self, xla_compile_counter):
        args = sim_args(enable_defense=True, defense_type="foolsgold")
        sim = build_async_sim(args)
        hyper = hyper_for(args)
        sim._bootstrap(hyper)
        sim._pour_step(hyper)
        assert sim.dispatch_stats["compiles"] == 1
        xla_compile_counter.reset()
        for _ in range(4):
            sim._pour_step(hyper)
        assert xla_compile_counter.delta() == 0


# --- byzantine containment ----------------------------------------------------

class TestByzantineContainment:
    @staticmethod
    def _param_dist(a, b):
        va = np.concatenate([np.asarray(jax.device_get(l)).ravel()
                             for l in jax.tree_util.tree_leaves(a)])
        vb = np.concatenate([np.asarray(jax.device_get(l)).ravel()
                             for l in jax.tree_util.tree_leaves(b)])
        return float(np.linalg.norm(va - vb) /
                     max(np.linalg.norm(va), 1e-12))

    def test_krum_keeps_byzantine_updates_out(self):
        """Attack vs attack-free, same defense/seed: krum must exclude
        the (wildly scaled) byzantine rows — the attacked trajectory
        stays near the attack-free one (the defense's tolerance: the
        attack can still flip WHICH honest row krum picks) and nowhere
        near the undefended collapse."""
        kw = dict(comm_round=12, byzantine_client_num=2)
        atk = dict(enable_attack=True, attack_type="byzantine_random",
                   attack_scale=10.0)
        clean = build_async_sim(sim_args(
            enable_defense=True, defense_type="krum", **kw)).run()
        defended = build_async_sim(sim_args(
            enable_defense=True, defense_type="krum", **kw, **atk)).run()
        undefended = build_async_sim(sim_args(**kw, **atk)).run()
        d_def = self._param_dist(clean["params"], defended["params"])
        d_und = self._param_dist(clean["params"], undefended["params"])
        assert d_def < 1.0, d_def          # same neighborhood as clean
        assert d_und > 10.0 * d_def, (d_def, d_und)  # undefended: wrecked
        assert defended["final_test_acc"] > 0.9
        assert undefended["final_test_acc"] < defended["final_test_acc"]

    def test_reputation_benches_byzantine_out_of_rotation(self):
        """Defense verdicts feed the reputation store; once the posterior
        brands the byzantine clients the arrival rotation stops
        re-dispatching them — the late pours contain honest clients
        only. (Benching onset varies a few pours with the mesh layout —
        krum selections flip on float-association noise — so the window
        asserts the end state, not the onset.)"""
        args = sim_args(comm_round=44, enable_defense=True,
                        defense_type="multi_krum", krum_param_m=2,
                        byzantine_client_num=2, enable_attack=True,
                        attack_type="byzantine_random", attack_scale=10.0,
                        client_selection="reputation")
        sim = build_async_sim(args)
        r = sim.run()
        rep = sim.selection.store.reputation
        assert rep[0] < 0.3 and rep[1] < 0.3, rep
        assert r["final_test_acc"] > 0.9
        late = {a["client"] for p in sim.chaos_ledger.pours()[-6:]
                for a in p["injected"]["arrivals"]}
        assert late and not (late & {0, 1}), sorted(late)

    def test_foolsgold_crash_resume_replays_identical_verdicts(
            self, tmp_path):
        from fedml_tpu.core.chaos import ChaosCrash
        kw = dict(comm_round=12, enable_defense=True,
                  defense_type="foolsgold", chaos_straggler_prob=0.2,
                  chaos_straggler_work=0.5, chaos_seed=13)
        ref = build_async_sim(sim_args(**kw))
        r_ref = ref.run()
        ck = dict(kw, checkpoint_dir=str(tmp_path / "ck"),
                  checkpoint_every_rounds=5, chaos_crash_at_round=7)
        crash = build_async_sim(sim_args(**ck))
        with pytest.raises(ChaosCrash):
            crash.run()
        resumed = build_async_sim(sim_args(**dict(
            ck, chaos_crash_at_round=None)))
        r_res = resumed.run()
        # identical pour trajectory AND identical defense history: the
        # base ring + defense state rode the async checkpoint
        leaves_equal(r_ref["params"], r_res["params"])
        leaves_equal(ref._defense_state["history"],
                     resumed._defense_state["history"])


# --- partial-pour row masks ---------------------------------------------------

class TestRowMasks:
    def _defend(self, defense, mat, w, mask=None, **hp_kw):
        from fedml_tpu.core.mesh import build_mesh
        from fedml_tpu.core.security.defense import sharded
        mesh = build_mesh(None)
        out = sharded.defend_matrix_sharded(
            mesh, AXIS_CLIENT, jnp.asarray(mat, jnp.float32),
            jnp.asarray(w, jnp.float32), defense,
            hp=sharded.DefenseHP(**hp_kw), row_mask=mask)
        vec = out[0] if isinstance(out, tuple) else out
        return np.asarray(jax.device_get(vec))

    def test_masked_median_matches_valid_rows_only(self):
        rng = np.random.default_rng(0)
        mat = rng.normal(size=(5, 12)).astype(np.float32)
        mat[3:] = 0.0  # padding rows
        mask = np.asarray([1, 1, 1, 0, 0], np.float32)
        got = self._defend("median", mat, np.ones(5), mask=mask)
        np.testing.assert_allclose(got, np.median(mat[:3], axis=0),
                                   rtol=1e-6, atol=1e-7)

    def test_masked_trimmed_mean_trims_within_valid_prefix(self):
        rng = np.random.default_rng(1)
        mat = np.zeros((6, 8), np.float32)
        mat[:4] = rng.normal(size=(4, 8))
        mask = np.asarray([1, 1, 1, 1, 0, 0], np.float32)
        got = self._defend("trimmed_mean", mat, np.ones(6), mask=mask,
                           trim_fraction=0.25)
        s = np.sort(mat[:4], axis=0)
        np.testing.assert_allclose(got, np.mean(s[1:3], axis=0),
                                   rtol=1e-5, atol=1e-6)

    def test_masked_krum_never_selects_padding(self):
        mat = np.zeros((4, 8), np.float32)
        mat[0] = 1.0
        mat[1] = 1.01
        # rows 2/3 are zero padding — closest pair by raw distances!
        mask = np.asarray([1, 1, 0, 0], np.float32)
        got = self._defend("krum", mat, np.ones(4), mask=mask)
        assert abs(float(np.mean(got)) - 1.0) < 0.1  # a REAL row won

    def test_masked_three_sigma_stats_ignore_padding(self):
        rng = np.random.default_rng(2)
        mat = np.zeros((6, 10), np.float32)
        mat[:4] = 1.0 + 0.01 * rng.normal(size=(4, 10))
        mask = np.asarray([1, 1, 1, 1, 0, 0], np.float32)
        # unmasked, the zero padding drags the coordinate median to ~0.5x
        # and every real row would look like an outlier; masked, all four
        # real rows are kept
        got = self._defend("three_sigma", mat, np.ones(6), mask=mask)
        np.testing.assert_allclose(
            got, np.mean(mat[:4], axis=0), rtol=1e-4, atol=1e-5)

    def test_mask_none_is_bit_identical_to_pre_mask_kernels(self):
        """The sync paths never pass a mask — all-ones behavior must be
        byte-identical to mask-free for a couple of sensitive kernels."""
        rng = np.random.default_rng(3)
        mat = rng.normal(size=(6, 16)).astype(np.float32)
        w = rng.uniform(1, 2, 6).astype(np.float32)
        for d in ("median", "trimmed_mean", "krum", "three_sigma", "wbc"):
            a = self._defend(d, mat, w)
            b = self._defend(d, mat, w, mask=np.ones(6, np.float32))
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


# --- adaptive rfa_iters (satellite) ------------------------------------------

class TestAdaptiveRFA:
    def test_host_kernel_exits_early_on_convergence(self):
        from fedml_tpu.core.security.defense import robust_agg
        rng = np.random.default_rng(0)
        tight = 1.0 + 1e-4 * rng.normal(size=(6, 32)).astype(np.float32)
        v_fixed, info_fixed = robust_agg.geometric_median(
            jnp.asarray(tight), jnp.ones(6), iters=64)
        v_tol, info_tol = robust_agg.geometric_median(
            jnp.asarray(tight), jnp.ones(6), iters=64, tol=1e-6)
        assert int(info_fixed["iters_run"]) == 64
        assert int(info_tol["iters_run"]) < 64
        np.testing.assert_allclose(np.asarray(v_tol), np.asarray(v_fixed),
                                   rtol=1e-5, atol=1e-6)

    def test_sharded_tol_matches_host_within_tolerance(self):
        from fedml_tpu.core.mesh import build_mesh
        from fedml_tpu.core.security.defense import robust_agg, sharded
        rng = np.random.default_rng(1)
        mat = rng.normal(size=(5, 24)).astype(np.float32)
        w = np.ones(5, np.float32)
        host, _ = robust_agg.geometric_median(jnp.asarray(mat),
                                              jnp.asarray(w), iters=32,
                                              tol=1e-5)
        mesh = build_mesh(None)
        shard = sharded.defend_matrix_sharded(
            mesh, AXIS_CLIENT, jnp.asarray(mat), jnp.asarray(w), "rfa",
            hp=sharded.DefenseHP(rfa_iters=32, rfa_tol=1e-5))
        np.testing.assert_allclose(np.asarray(jax.device_get(shard)),
                                   np.asarray(host), rtol=1e-4, atol=1e-5)

    def test_defender_wires_the_tol_knob(self):
        from fedml_tpu.core.security import FedMLDefender
        dfd = FedMLDefender(Arguments(enable_defense=True,
                                      defense_type="rfa", rfa_tol=1e-4))
        assert dfd.rfa_tol == 1e-4
        assert FedMLDefender(Arguments(enable_defense=True,
                                       defense_type="rfa")).rfa_tol == 0.0


# --- cross-silo async defended pours -----------------------------------------

class TestCrossSiloDefendedPours:
    def _agg(self, **kw):
        from fedml_tpu.cross_silo.server.async_server import \
            AsyncFedMLAggregator
        args = Arguments(client_num_per_round=4,
                         round_mode="async_buffered", async_buffer_k=2,
                         async_alpha=1.0,
                         async_staleness_weighting="constant",
                         async_staleness_cap=4, **kw)
        return AsyncFedMLAggregator(args,
                                    {"w": np.zeros((3,), np.float32)})

    def test_defended_pour_rebases_and_records_verdicts(self):
        agg = self._agg(enable_defense=True, defense_type="krum",
                        byzantine_client_num=1)
        agg.add_async_upload(1, {"w": np.asarray([1., 0., 0.], np.float32)},
                             1.0, up_version=0, arrival_t=0.0,
                             compressed=False)
        agg.add_async_upload(2, {"w": np.asarray([1.1, .1, 0.], np.float32)},
                             1.0, up_version=0, arrival_t=1.0,
                             compressed=False)
        agg.pour()
        v1 = np.asarray(agg.global_params["w"]).copy()
        # silo 3 trained from v0 (stale): its upload targets v0+delta;
        # re-based at v1 the delta is (upload - v0) - (v1 - v0)
        up3 = np.asarray([1.0, 0.0, 0.5], np.float32)
        agg.add_async_upload(3, {"w": up3}, 1.0, up_version=0,
                             arrival_t=2.0, compressed=False)
        agg.add_async_upload(1, {"w": v1 + np.asarray([1., 0., 0.],
                                                      np.float32)},
                             1.0, up_version=1, arrival_t=3.0,
                             compressed=False)
        agg.pour()
        assert agg.version == 2
        # krum picked ONE re-based row; both candidates are valid model
        # deltas, so the result is v1 + merge_scale * that row
        got = np.asarray(agg.global_params["w"])
        cands = [up3 - v1, np.asarray([1., 0., 0.], np.float32)]
        stal_w = np.asarray(agg.staleness_fn(np.asarray([1.0, 0.0])))
        ms = 1.0 * float(np.sum(stal_w)) / 2.0
        assert any(np.allclose(got, v1 + ms * c, rtol=1e-5)
                   for c in cands), (got, v1, cands)
        # verdict evidence landed in the silo reputation stream
        obs = agg.silo_stats.incl_obs + agg.silo_stats.excl_obs
        assert float(np.sum(obs)) > 0

    def test_silo_reputation_benches_in_select_silos(self):
        agg = self._agg(enable_defense=True, defense_type="krum",
                        client_selection="reputation")
        # brand silo 2 as consistently excluded
        for _ in range(12):
            agg.silo_stats.record_verdict([1, 2, 3], [1.0, 0.0, 1.0])
        sel = agg.select_silos([1, 2, 3])
        assert 2 not in sel and {1, 3} <= set(sel)
        # uniform default: everyone, unchanged
        agg_u = self._agg(enable_defense=True, defense_type="krum")
        for _ in range(12):
            agg_u.silo_stats.record_verdict([1, 2, 3], [1.0, 0.0, 1.0])
        assert agg_u.select_silos([1, 2, 3]) == [1, 2, 3]

    def test_refusals(self):
        with pytest.raises(ValueError, match="weak_dp"):
            self._agg(enable_defense=True, defense_type="weak_dp")
        with pytest.raises(ValueError, match="async_buffered"):
            self._agg(enable_dp=True, dp_epsilon=1.0, dp_delta=1e-5,
                      dp_clip=1.0)


# --- stats-driven silo DATA-index assignment (satellite) ---------------------

class TestSiloIndexAssignment:
    def _agg(self, **kw):
        from fedml_tpu.cross_silo.server.fedml_aggregator import \
            FedMLAggregator
        return FedMLAggregator(Arguments(client_num_per_round=3, **kw),
                               {"w": np.zeros(2, np.float32)})

    def test_legacy_is_round_robin(self):
        agg = self._agg()
        assert agg.assign_data_indices([1, 2, 3], [10, 20, 30, 40]) == \
            {1: 10, 2: 20, 3: 30}
        # wraps like the reference's i % len
        assert agg.assign_data_indices([1, 2, 3], [10, 20]) == \
            {1: 10, 2: 20, 3: 10}

    def test_scored_routes_first_indices_to_best_silos(self):
        agg = self._agg(silo_index_assignment="scored")
        for _ in range(6):
            agg.silo_stats.record_availability(1, participated=False)
            agg.silo_stats.record_availability(2, participated=True)
            agg.silo_stats.record_availability(3, participated=True)
        agg.silo_stats.record_latency(3, 1.0)
        agg.silo_stats.record_latency(2, 9.0)
        agg.silo_stats.record_latency(1, 9.0)
        got = agg.assign_data_indices([1, 2, 3], [10, 20, 30])
        assert got[3] == 10 and got[1] == 30

    def test_scored_cold_store_degrades_to_legacy(self):
        agg = self._agg(silo_index_assignment="scored")
        assert agg.assign_data_indices([1, 2, 3], [10, 20, 30]) == \
            {1: 10, 2: 20, 3: 30}

    def test_unknown_mode_refused(self):
        agg = self._agg(silo_index_assignment="best_effort")
        with pytest.raises(ValueError, match="silo_index_assignment"):
            agg.assign_data_indices([1, 2], [10, 20])


# --- async-aware dispatch (satellite) ----------------------------------------

class TestAsyncDispatch:
    def test_oort_and_poc_rank_the_idle_pool(self):
        for sel in ("oort", "power_of_choice"):
            args = sim_args(comm_round=6, client_selection=sel)
            sim = build_async_sim(args)
            r = sim.run()
            assert r["rounds"] == 6
            assert sim.dispatch_stats["compiles"] == 1
            assert sim.selection.track

    def test_ranking_is_deterministic_given_history(self):
        args = sim_args(client_selection="oort")
        sim = build_async_sim(args)
        for c in range(8):
            sim.selection.store.record_loss(c, float(8 - c))
            sim.selection.store.record_arrival(c, 1.0 + 0.1 * c)
        from collections import deque
        sim._idle = deque(range(8))
        sim._rank_idle()
        first = list(sim._idle)
        sim._idle = deque(range(8))
        sim._rank_idle()
        assert list(sim._idle) == first
        # high loss / fast arrival wins the head of the rotation
        assert first[0] == 0

    def test_adaptive_oversample_is_pinned_not_refused(self):
        sim = build_async_sim(sim_args(comm_round=2,
                                       selection_adaptive_oversample=True))
        assert not sim.selection.adaptive


# --- full DEFENSE_TYPES composition sweep (slow: ~20 program compiles) -------

@pytest.mark.slow
def test_every_defense_composes_or_refuses_documented():
    """The acceptance criterion verbatim: ``round_mode: async_buffered``
    composes with every defense in DEFENSE_TYPES — one real defended
    pour each — or refuses per-defense with the documented reason
    (weak_dp/crfl: per-pour noise accounting is the async-DP open
    design)."""
    from fedml_tpu.core.security import DEFENSE_TYPES

    refused = {"weak_dp", "crfl"}
    for d in DEFENSE_TYPES:
        kw = dict(comm_round=2, client_num_in_total=4,
                  client_num_per_round=4, batch_size=16,
                  enable_defense=True, defense_type=d,
                  byzantine_client_num=1)
        if d in refused:
            with pytest.raises(ValueError, match="noise-adding"):
                build_async_sim(sim_args(**kw))
            continue
        sim = build_async_sim(sim_args(**kw))
        hyper = hyper_for(sim.args)
        sim._bootstrap(hyper)
        sim._pour_step(hyper)
        assert sim.version >= 1, d
        assert sim.dispatch_stats["compiles"] == 1, d
        for leaf in jax.tree_util.tree_leaves(sim.params):
            assert np.all(np.isfinite(np.asarray(jax.device_get(leaf)))), d


# --- the byzantine chaos soak (slow) -----------------------------------------

@pytest.mark.slow
@pytest.mark.chaos
def test_async_byzantine_chaos_soak_200_pours():
    """200 defended pours with byzantine attackers riding the pour
    program ON TOP of dropout + straggler faults: the engine must never
    stall, the buffer ledger must balance, the model must still learn,
    and the reputation store must end the run with the byzantine clients
    branded below the honest cohort."""
    args = sim_args(comm_round=200, client_num_in_total=8,
                    client_num_per_round=8,
                    enable_defense=True, defense_type="multi_krum",
                    krum_param_m=2, byzantine_client_num=2,
                    enable_attack=True, attack_type="byzantine_random",
                    attack_scale=10.0, client_selection="reputation",
                    chaos_dropout_prob=0.15, chaos_straggler_prob=0.2,
                    chaos_straggler_work=0.5, chaos_seed=23)
    sim = build_async_sim(args)
    r = sim.run()
    assert r["rounds"] == 200
    assert sim.dispatch_stats["compiles"] == 1
    c = sim.buffer.counters
    pours = sim.chaos_ledger.pours()
    assert len(pours) == 200
    assert sum(p["observed"]["poured"] for p in pours) == \
        sim.updates_aggregated
    rep = sim.selection.store.reputation
    assert rep[0] < 0.5 and rep[1] < 0.5
    # krum-style defenses exclude honest clients every pour too, so the
    # per-client floor is noisy — the POPULATION signal is what must
    # hold: honest clients average clearly above the byzantine pair
    assert float(np.mean(rep[2:])) > 1.5 * float(np.max(rep[:2]))
    assert r["final_test_acc"] > 0.9
