"""Durable multi-tenant fleet plane (ISSUE 18): the sqlite device
registry's upsert/claims/fairness semantics, pacer-driven cohort sizing,
the concurrent task plane, and the restart-and-resume story — a
restarted server replays *identical* cohorts from the persisted registry
plus checkpointed stats/pacer posture."""

import threading

import numpy as np
import pytest

from fedml_tpu import data as data_mod
from fedml_tpu import model as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.fleet import DeviceRegistry, TaskPlane
from fedml_tpu.core.selection import DeadlinePacer

pytestmark = pytest.mark.fleet


class TestDeviceRegistry:
    def test_register_upsert_is_idempotent(self, tmp_path):
        """Re-registering under the same id (network flap, app restart)
        refreshes eligibility + last_heard in place — never a duplicate
        row, never a reset of first_seen."""
        reg = DeviceRegistry(str(tmp_path / "fleet.db"))
        reg.register(5, {"os": "android", "charging": True}, now=100.0)
        reg.register(5, {"os": "android", "charging": False}, now=200.0)
        assert reg.device_count() == 1
        d = reg.device(5)
        assert d["registrations"] == 2
        assert d["first_seen"] == 100.0
        assert d["last_heard"] == 200.0
        assert d["charging"] is False  # refreshed, not stale

    def test_claims_grant_one_task_per_round(self, tmp_path):
        reg = DeviceRegistry(str(tmp_path / "fleet.db"))
        for i in range(1, 6):
            reg.register(i, now=0.0)
        g1, b1, c1 = reg.claim("train", [1, 2, 3], 0, now=1.0)
        assert g1 == [1, 2, 3] and b1 == 0 and c1 == 0
        # another task wanting an overlapping set only gets the free one
        g2, b2, c2 = reg.claim("fa", [2, 3, 4], 0, now=1.0)
        assert g2 == [4] and b2 == 2 and c2 == 0
        # a retry by the SAME task is idempotent — no double-claim, no
        # busy denial against itself
        g3, b3, c3 = reg.claim("train", [1, 2, 3], 0, now=1.5)
        assert g3 == [1, 2, 3] and b3 == 0
        # release frees the round's claims and appends participation
        reg.release("train", 0, [1, 2, 3], now=2.0)
        g4, _, _ = reg.claim("fa", [1], 1, now=2.5)
        assert g4 == [1]

    def test_fairness_cap_denies_over_window(self, tmp_path):
        reg = DeviceRegistry(str(tmp_path / "fleet.db"))
        reg.register(1, now=0.0)
        # two served rounds inside the window
        for r in range(2):
            g, _, _ = reg.claim("train", [1], r, cap=2, window_s=100.0,
                                now=10.0 * (r + 1))
            assert g == [1]
            reg.release("train", r, [1], now=10.0 * (r + 1) + 1)
        # at the cap: denied
        g, busy, capped = reg.claim("train", [1], 2, cap=2, window_s=100.0,
                                    now=30.0)
        assert g == [] and busy == 0 and capped == 1
        # outside the window the history no longer counts
        g, _, capped = reg.claim("train", [1], 3, cap=2, window_s=100.0,
                                 now=500.0)
        assert g == [1] and capped == 0

    def test_audit_detects_overlap_and_cap_breach(self, tmp_path):
        reg = DeviceRegistry(str(tmp_path / "fleet.db"))
        reg.register(1, now=0.0)
        assert reg.audit(cap=1, window_s=100.0) == {"overlap": 0,
                                                    "cap_violations": 0}
        # two tasks recording the same (device, round): overlap
        reg.release("train", 0, [1], now=1.0)
        reg.release("fa", 0, [1], now=2.0)
        out = reg.audit(cap=1, window_s=100.0)
        assert out["overlap"] == 1
        assert out["cap_violations"] == 1  # 2 served rounds, cap 1

    def test_iter_id_chunks_pages_ascending(self, tmp_path):
        reg = DeviceRegistry(str(tmp_path / "fleet.db"))
        for i in range(10):
            reg.register(i, now=0.0)
        chunks = list(reg.iter_id_chunks(chunk=4))
        assert [len(c) for c in chunks] == [4, 4, 2]
        np.testing.assert_array_equal(np.concatenate(chunks),
                                      np.arange(10))

    def test_state_blob_roundtrip(self, tmp_path):
        reg = DeviceRegistry(str(tmp_path / "fleet.db"))
        arrays = {"a": np.arange(5, dtype=np.float64),
                  "b": np.int64(7)}
        reg.save_state("fleet:pacer:train", arrays, now=1.0)
        back = reg.load_state("fleet:pacer:train")
        np.testing.assert_array_equal(back["a"], arrays["a"])
        assert int(back["b"]) == 7
        assert reg.load_state("missing") is None
        assert "fleet:pacer:train" in reg.state_keys()


class TestPacerCohortSizing:
    def _args(self, **kw):
        return Arguments(**kw)

    def test_off_is_identity(self):
        pacer = DeadlinePacer.from_args(self._args())
        assert pacer.paced_cohort(17) == 17
        for _ in range(20):
            pacer.observe_utility(1.0)  # no-op when off
        assert pacer.paced_cohort(17) == 17
        assert pacer.cohort_scale == 1.0

    def test_grows_on_saturation_decays_on_improvement(self):
        pacer = DeadlinePacer.from_args(self._args(
            pacer_adapt_cohort=True, pacer_util_window=2))
        # flat utility: the second window shows no improvement -> grow k
        for u in (1.0, 1.0, 1.0, 1.0):
            pacer.observe_utility(u)
        assert pacer.cohort_scale == pytest.approx(1.2)
        assert pacer.paced_cohort(10) > 10
        # strongly improving utility: decay back toward the floor
        for u in (10.0, 10.0):
            pacer.observe_utility(u)
        assert pacer.cohort_scale == pytest.approx(1.2 * 0.9)
        for u in (100.0, 100.0):
            pacer.observe_utility(u)
        assert pacer.cohort_scale == 1.0  # clamped at the floor
        # bounds hold under sustained saturation
        for _ in range(200):
            pacer.observe_utility(1.0)
        assert pacer.cohort_scale <= pacer.max_cohort_scale

    def test_state_roundtrip_and_legacy_load(self):
        args = self._args(pacer_adapt_cohort=True, pacer_util_window=2)
        pacer = DeadlinePacer.from_args(args)
        for u in (1.0, 1.0, 1.0, 1.0, 2.0):
            pacer.observe_utility(u)
        st = pacer.state_dict()
        other = DeadlinePacer.from_args(args)
        other.load_state_dict(st)
        assert other.cohort_scale == pacer.cohort_scale
        assert other._util_hist == pacer._util_hist
        # a pre-ISSUE-18 snapshot (no cohort keys) still loads
        legacy = {k: v for k, v in st.items()
                  if k not in ("cohort_scale", "util_hist")}
        fresh = DeadlinePacer.from_args(args)
        fresh.load_state_dict(legacy)
        assert fresh.cohort_scale == 1.0


def plane_args(**kw):
    base = dict(random_seed=7, cohort_scan_chunk=64, oort_alpha=0.0,
                pacer_over_sample=1.0)
    base.update(kw)
    return Arguments(**base)


def seeded_plane(tmp_path, name, n=64, **kw):
    reg = DeviceRegistry(str(tmp_path / f"{name}.db"))
    for i in range(n):
        reg.register(i, now=0.0)
    plane = TaskPlane(plane_args(**kw), reg, population=n)
    return reg, plane


class TestTaskPlane:
    def test_three_tasks_share_one_population_fairly(self, tmp_path):
        """3 concurrent tasks (train / FA / LoRA shapes) over one
        registry: per-round cohorts are disjoint, every task gets its
        full k, and the registry audit finds zero fairness violations."""
        reg, plane = seeded_plane(tmp_path, "fleet", n=64,
                                  fleet_max_rounds_per_window=4,
                                  fleet_fairness_window_s=1000.0)
        plane.add_task("train", cohort_k=12)
        plane.add_task("fa", cohort_k=8, kind="analytics")
        plane.add_task("lora", cohort_k=4, kind="llm")
        for r in range(6):
            now = 10.0 * (r + 1)
            cohorts = plane.assign_round(now=now)
            all_ids = [d for c in cohorts.values() for d in c]
            assert len(all_ids) == len(set(all_ids)), "cohorts overlap"
            assert len(cohorts["train"]) == 12
            assert len(cohorts["fa"]) == 8
            assert len(cohorts["lora"]) == 4
            for tid, cohort in cohorts.items():
                plane.observe_round(tid, cohort, wall_s=0.1, now=now + 1)
        assert reg.audit(cap=4, window_s=1000.0) == \
            {"overlap": 0, "cap_violations": 0}
        # the cap actually bit: 6 rounds x 24 slots over 64 devices
        # cannot all go to the same devices
        counts = reg.participation_counts(list(range(64)), 1000.0,
                                          now=100.0)
        assert counts.max() <= 4
        assert plane.task("train").rounds_run == 6

    def test_cap_starves_gracefully(self, tmp_path):
        """When the fairness cap exhausts the eligible population, the
        cohort shrinks instead of violating the cap."""
        reg, plane = seeded_plane(tmp_path, "tiny", n=8,
                                  fleet_max_rounds_per_window=1,
                                  fleet_fairness_window_s=1000.0)
        plane.add_task("train", cohort_k=6)
        sizes = []
        for r in range(3):
            now = 10.0 * (r + 1)
            cohorts = plane.assign_round(now=now)
            sizes.append(len(cohorts["train"]))
            plane.observe_round("train", cohorts["train"], wall_s=0.1,
                                now=now + 1)
        # 8 devices, cap 1: round 0 serves 6, round 1 the remaining 2,
        # round 2 nobody — and the audit stays clean
        assert sizes == [6, 2, 0]
        assert reg.audit(cap=1, window_s=1000.0) == \
            {"overlap": 0, "cap_violations": 0}

    def test_restart_resumes_identical_cohorts(self, tmp_path):
        """The acceptance replay: plane A runs 2 rounds and checkpoints;
        plane B (fresh objects, same registry) loads and runs rounds
        2-3; twin C runs all 4 uninterrupted on its own registry. B's
        resumed rounds must equal C's — the persisted registry +
        stats/pacer snapshot IS the plane's whole state."""
        kw = dict(fleet_max_rounds_per_window=3,
                  fleet_fairness_window_s=1000.0,
                  pacer_adapt_cohort=True, pacer_util_window=2)

        def run(plane, reg, rounds, start=0, log=None):
            for r in range(start, rounds):
                now = 10.0 * (r + 1)
                cohorts = plane.assign_round(now=now)
                for tid, cohort in cohorts.items():
                    plane.observe_round(tid, cohort, wall_s=0.1,
                                        now=now + 1)
                plane.save(now=now + 2)
                if log is not None:
                    log.append((r, cohorts))

        reg_a, plane_a = seeded_plane(tmp_path, "shared", n=48, **kw)
        plane_a.add_task("train", cohort_k=8)
        plane_a.add_task("fa", cohort_k=4, kind="analytics")
        run(plane_a, reg_a, rounds=2)

        # B: brand-new objects over the SAME registry file
        reg_b = DeviceRegistry(str(tmp_path / "shared.db"))
        plane_b = TaskPlane(plane_args(**kw), reg_b, population=48)
        plane_b.add_task("train", cohort_k=8)
        plane_b.add_task("fa", cohort_k=4, kind="analytics")
        assert plane_b.load() is True
        assert plane_b.round_cursor == 2  # resumes where A stopped
        log_b = []
        run(plane_b, reg_b, rounds=4, start=2, log=log_b)

        # C: the uninterrupted twin on its own registry
        reg_c, plane_c = seeded_plane(tmp_path, "twin", n=48, **kw)
        plane_c.add_task("train", cohort_k=8)
        plane_c.add_task("fa", cohort_k=4, kind="analytics")
        log_c = []
        run(plane_c, reg_c, rounds=4, log=log_c)

        assert log_b == log_c[2:], \
            "resumed plane diverged from the uninterrupted twin"
        # a cold plane on a fresh registry has nothing to load
        reg_d, plane_d = seeded_plane(tmp_path, "cold", n=48, **kw)
        assert plane_d.load() is False

    def test_concurrent_claims_from_threads_never_overlap(self, tmp_path):
        """Two task servers hammering the SAME registry file from
        separate threads (the cross-process story, minus the fork), both
        wanting overlapping device sets and HOLDING their claims while
        the other claims: BEGIN IMMEDIATE keeps every round's
        assignments disjoint."""
        reg_path = str(tmp_path / "shared.db")
        reg = DeviceRegistry(reg_path)
        for i in range(40):
            reg.register(i, now=0.0)
        grants = {}
        # both sides hold their claims until the other has claimed too —
        # the simultaneous-tenancy window the claims table must arbitrate
        rendezvous = threading.Barrier(2, timeout=30)
        wanted = {"train": list(range(0, 30)), "fa": list(range(10, 40))}

        def worker(task_id):
            own = DeviceRegistry(reg_path)  # own connection pool
            got = []
            for r in range(5):
                g, _, _ = own.claim(task_id, wanted[task_id], r,
                                    now=float(r + 1))
                rendezvous.wait()  # both tasks now hold claims
                got.append(set(g))
                own.release(task_id, r, sorted(g), now=float(r + 1) + 0.5)
                rendezvous.wait()  # both released; next round
            grants[task_id] = got

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in ("train", "fa")]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert set(grants) == {"train", "fa"}
        for r in range(5):
            assert not (grants["train"][r] & grants["fa"][r]), \
                f"round {r}: both tasks held the same device"
            # nothing in the contended middle went unserved
            assert grants["train"][r] | grants["fa"][r] == set(range(40))
        assert reg.audit() == {"overlap": 0, "cap_violations": 0}


# --- e2e: the cross-device session over a durable registry ---------------


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=3, client_num_per_round=3,
                comm_round=2, epochs=1, batch_size=32, learning_rate=0.1,
                random_seed=3, training_type="cross_device",
                cohort_assembly=True, cohort_size=2,
                # determinism for replay assertions: no wall-clock
                # latency term in the oort score, no over-sampled
                # dispatch (the barrier then equals the cohort, so the
                # served set is the cohort — thread timing can't leak
                # into the stats evidence)
                oort_alpha=0.0, pacer_over_sample=1.0)
    base.update(kw)
    return Arguments(**base)


def run_session(tmp_path, cache="cache", **kw):
    """One in-proc cross-device session; returns the server (result,
    cohort_log, fleet handle all inspectable)."""
    from fedml_tpu.core.distributed.communication.inproc import InProcBroker
    from fedml_tpu.cross_device import (build_device_client,
                                        build_device_server)

    args = make_args(model_file_cache_dir=str(tmp_path / cache), **kw)
    args.inproc_broker = InProcBroker()
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    server = build_device_server(args, fed, bundle, backend="INPROC")
    n = int(args.client_num_per_round)
    devices = [build_device_client(args, fed, bundle, device_id=i,
                                   backend="INPROC")
               for i in range(1, n + 1)]
    threads = [threading.Thread(target=d.run, daemon=True)
               for d in devices]
    for t in threads:
        t.start()
    done = {}

    def run_server():
        server.run()
        done["ok"] = True

    st = threading.Thread(target=run_server, daemon=True)
    st.start()
    st.join(timeout=120)
    assert done.get("ok"), "server stalled"
    return server


class TestServerRestartResume:
    def test_restarted_server_resumes_and_replays(self, tmp_path):
        """Kill-and-restart across sessions: session A (2 of 4 rounds)
        checkpoints into the registry; session B reopens it and must
        (a) remember A's devices, (b) resume at round 2 with the
        aggregated model, and (c) schedule the SAME rounds 2-3 cohorts
        as an uninterrupted twin running all 4 rounds."""
        db = str(tmp_path / "fleet.db")
        a = run_session(tmp_path, cache="a", comm_round=2,
                        fleet_registry=db)
        assert len(a.result["history"]) == 2
        assert a.fleet.device_count() == 3
        assert a.round_idx == 2  # persisted cursor

        b = run_session(tmp_path, cache="b", comm_round=4,
                        fleet_registry=db)
        # remembered, not re-discovered: same rows, bumped counters
        assert b.fleet.device_count() == 3
        assert b.fleet.device(1)["registrations"] == 2
        # only rounds 2-3 ran in session B
        assert len(b.result["history"]) == 2
        assert b.cohort_log[0][0] == 2

        c = run_session(tmp_path, cache="c", comm_round=4,
                        fleet_registry=str(tmp_path / "twin.db"))
        assert len(c.result["history"]) == 4
        assert b.cohort_log == c.cohort_log[2:], \
            "restarted server diverged from the uninterrupted twin"
        # the resumed model kept learning (restart did not reset it)
        assert b.result["final_test_acc"] >= a.result["final_test_acc"]

    def test_completed_session_restart_is_a_noop(self, tmp_path):
        """Restarting after the final round: the registry remembers the
        session completed — the server finishes immediately instead of
        re-training."""
        db = str(tmp_path / "fleet.db")
        run_session(tmp_path, cache="a", comm_round=2, fleet_registry=db)
        again = run_session(tmp_path, cache="b", comm_round=2,
                            fleet_registry=db)
        assert again.result["history"] == []
        assert again.round_idx == 2

    def test_fleet_off_path_is_unchanged(self, tmp_path):
        """The bit-identity gate: with no fleet_registry the server
        schedules exactly what a fleet-on server over a FRESH registry
        schedules (the registry only adds memory, never perturbs a cold
        cohort) — and no registry file is ever created."""
        off = run_session(tmp_path, cache="off", comm_round=2)
        assert off.fleet is None
        on = run_session(tmp_path, cache="on", comm_round=2,
                         fleet_registry=str(tmp_path / "fresh.db"))
        assert off.cohort_log == on.cohort_log
        assert off.result["final_test_acc"] == \
            on.result["final_test_acc"]
        assert not (tmp_path / "off" / "fleet.db").exists()


class TestFACohortAssembly:
    def _session(self, n=4, eligibility=None, **kw):
        from fedml_tpu.core.distributed.communication.inproc import \
            InProcBroker
        from fedml_tpu.fa.analyzers import AvgAggregator, AvgClientAnalyzer
        from fedml_tpu.fa.cross_silo import (FAClientManager,
                                             FAServerManager)

        rng = np.random.RandomState(0)
        datas = [rng.randn(50) * (i + 1) for i in range(n)]
        args = Arguments(comm_round=3, client_num_per_round=n,
                         training_type="cross_silo", random_seed=5,
                         oort_alpha=0.0, pacer_over_sample=1.0, **kw)
        args.inproc_broker = InProcBroker()
        server = FAServerManager(args, AvgAggregator(), rank=0,
                                 size=n + 1, backend="INPROC")
        eligs = eligibility or {}
        clients = [FAClientManager(args, AvgClientAnalyzer(), datas[i],
                                   rank=i + 1, size=n + 1,
                                   backend="INPROC",
                                   eligibility=eligs.get(i + 1))
                   for i in range(n)]
        threads = [threading.Thread(target=c.run, daemon=True)
                   for c in clients]
        for t in threads:
            t.start()
        done = {}

        def run_server():
            server.run()
            done["ok"] = True

        st = threading.Thread(target=run_server, daemon=True)
        st.start()
        st.join(timeout=120)
        assert done.get("ok"), "fa server stalled"
        return server

    def test_fa_cohort_filters_ineligible_party(self):
        """Analytics rides the same eligibility sieve as training: a
        party reporting not-charging is never scheduled while
        cohort_require_charging is on, and rounds still close on the
        eligible cohort."""
        server = self._session(
            n=4, cohort_assembly=True, cohort_size=2,
            cohort_require_charging=True,
            eligibility={2: {"charging": False}})
        assert server.result is not None
        assert server.result["rounds"] == 3
        assert len(server.cohort_log) == 3
        for _, cohort in server.cohort_log:
            assert len(cohort) == 2
            assert 2 not in cohort, "ineligible party was scheduled"
        sel = server.stats.times_selected_for([1, 2, 3, 4])
        assert sel[1] == 0

    def test_fa_cohort_off_is_broadcast(self):
        """Knob off: every online party analyzes every round — the
        legacy FA session byte-for-byte."""
        server = self._session(n=3)
        assert not server.cohort_enabled
        assert server.stats is None
        assert server.result["rounds"] == 3
