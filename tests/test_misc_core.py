"""Compression, centralized baseline, pluggable ServerAggregator."""

import jax
import jax.numpy as jnp
import numpy as np

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner
from fedml_tpu import data as data_mod
from fedml_tpu import model as model_mod
from fedml_tpu.utils.compression import (compress_tree, decompress,
                                         decompress_tree, randk_compress,
                                         topk_compress)


class TestCompression:
    def test_topk_keeps_largest(self):
        v = jnp.asarray([0.1, -5.0, 0.2, 3.0, -0.05])
        vals, idx = topk_compress(v, 2)
        assert set(np.asarray(idx).tolist()) == {1, 3}
        out = decompress(vals, idx, 5)
        np.testing.assert_allclose(np.asarray(out),
                                   [0, -5.0, 0, 3.0, 0], atol=1e-7)

    def test_randk_unbiased(self):
        v = jnp.asarray(np.random.RandomState(0).randn(100).astype(
            np.float32))
        d, k, trials = 100, 20, 300
        outs = []
        for i in range(trials):
            vals, idx = randk_compress(v, k, jax.random.PRNGKey(i))
            outs.append(np.asarray(decompress(vals, idx, d)))
        # per-coordinate estimator std: each trial contributes v_i*(d/k)
        # w.p. k/d, so var = v_i^2*(d/k - 1); bound the mean's error at
        # 4.5 sigma (PRNG-stream-independent, ~sound for 100 coordinates)
        sigma = np.abs(np.asarray(v)) * np.sqrt(d / k - 1) / np.sqrt(trials)
        err = np.abs(np.mean(outs, 0) - np.asarray(v))
        assert np.all(err <= 4.5 * sigma + 1e-3), (
            f"max z-score {np.max(err / (sigma + 1e-9)):.2f}")

    def test_tree_roundtrip(self):
        tree = {"a": jnp.ones((4, 3)), "b": jnp.arange(5.0)}
        blob = compress_tree(tree, ratio=1.0)
        out = decompress_tree(blob, tree)
        np.testing.assert_allclose(np.asarray(out["a"]), np.ones((4, 3)))
        np.testing.assert_allclose(np.asarray(out["b"]), np.arange(5.0))


def test_centralized_baseline_learns():
    args = Arguments(dataset="synthetic_mnist", model="lr",
                     client_num_in_total=8, batch_size=32,
                     learning_rate=0.1, comm_round=6, epochs=1,
                     federated_optimizer="centralized",
                     frequency_of_the_test=5, random_seed=0)
    r = fedml_tpu.run_simulation(backend="tpu", args=args)
    assert r["final_test_acc"] > 0.7, r["history"]


def test_pluggable_server_aggregator():
    """A user ServerAggregator (reference core/alg_frame ABC) drives the
    mesh engine's aggregation; a median aggregator must still learn."""
    from fedml_tpu.core.algframe.server_aggregator import ServerAggregator

    calls = {"n": 0}

    class MedianAggregator(ServerAggregator):
        def aggregate(self, mat, weights):
            calls["n"] += 1
            return jnp.median(mat, axis=0)

    args = Arguments(dataset="synthetic_mnist", model="lr",
                     client_num_in_total=4, client_num_per_round=4,
                     comm_round=3, batch_size=32, learning_rate=0.1,
                     frequency_of_the_test=2, random_seed=0)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    runner = FedMLRunner(args, dataset=fed, model=bundle,
                         server_aggregator=MedianAggregator())
    r = runner.run()
    assert calls["n"] == 3
    assert r["final_test_acc"] > 0.6, r["history"]
