"""Buffered-async federated rounds (ISSUE 6, ``core/async_rounds``).

Covers the staleness math (weighting monotonicity, caps, the relative-mix
vs absolute-merge-scale split), buffer pour determinism under a seeded
arrival order, the TPU engine's ``round_mode: async_buffered`` (learning,
compile-once double-buffered dispatch, crash-resume through
RoundCheckpointer, loud config refusals), the ``round_mode: sync``
bit-identity regression, the cross-silo async aggregator's staleness-
weighted pour + base ring, and the retry-budget deadline satellite.
The in-proc async WAN session and the 200-pour chaos soak are slow-marked.
"""

import heapq
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core.async_rounds import (UpdateBuffer, adaptive_staleness_cap,
                                         buffer_k_from_args, client_durations,
                                         make_staleness_fn, pour_weights,
                                         round_mode_from_args)

pytestmark = pytest.mark.async_rounds


def sim_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=8, client_num_per_round=8,
                comm_round=6, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=0, random_seed=3)
    base.update(kw)
    return Arguments(**base)


def build_async_sim(args):
    from fedml_tpu import data as data_mod, model as model_mod
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.async_engine import AsyncBufferedSimulator

    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    return AsyncBufferedSimulator(args, fed, bundle,
                                  create_optimizer(args, spec), spec)


def hyper_for(args):
    from fedml_tpu.core.algframe.types import TrainHyper
    return TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                      epochs=int(args.epochs))


# --- staleness weighting ------------------------------------------------------

class TestWeighting:
    def test_constant_is_one_everywhere(self):
        fn = make_staleness_fn("constant", cap=8)
        assert np.all(fn(np.arange(50)) == 1.0)

    def test_polynomial_monotone_decreasing_in_unit_interval(self):
        fn = make_staleness_fn("polynomial", poly_a=0.5, cap=32)
        w = fn(np.arange(0, 33))
        assert w[0] == 1.0
        assert np.all(np.diff(w) < 0)
        assert np.all((w > 0) & (w <= 1.0))

    def test_hinge_free_until_b_then_decays(self):
        fn = make_staleness_fn("hinge", poly_a=0.5, hinge_b=4, cap=32)
        w = fn(np.arange(0, 33))
        assert np.all(w[:5] == 1.0)          # s <= b: no penalty
        assert np.all(np.diff(w[4:]) < 0)    # past b: strict decay
        assert np.all(w > 0)

    def test_cap_saturates_instead_of_dropping(self):
        fn = make_staleness_fn("polynomial", poly_a=1.0, cap=8)
        assert fn(8) == fn(100) == fn(10**6)
        assert fn(100) > 0.0  # down-weighted, never zeroed

    def test_bad_knobs_refused(self):
        with pytest.raises(ValueError):
            make_staleness_fn("exponential")
        with pytest.raises(ValueError):
            make_staleness_fn("polynomial", poly_a=-1.0)

    def test_pour_weights_split(self):
        fn = make_staleness_fn("polynomial", poly_a=0.5, cap=16)
        w = np.asarray([2.0, 1.0, 1.0])
        # all fresh: relative mix is the plain weighted mean, merge scale
        # is exactly alpha
        nw, ms = pour_weights(w, np.zeros(3), fn, alpha=0.6)
        np.testing.assert_allclose(nw, w / w.sum(), rtol=1e-6)
        assert ms == pytest.approx(0.6)
        # staler pour: same relative shape question, SMALLER merge scale
        nw2, ms2 = pour_weights(w, np.asarray([4, 4, 4]), fn, alpha=0.6)
        np.testing.assert_allclose(nw2, w / w.sum(), rtol=1e-6)
        assert ms2 < ms
        # mixed staleness: the stale update loses relative weight too
        nw3, _ = pour_weights(np.ones(2), np.asarray([0, 9]), fn, 0.6)
        assert nw3[0] > nw3[1]
        assert nw3.sum() == pytest.approx(1.0)

    def test_zero_valued_knobs_are_honored(self):
        # 0 is legitimate for these knobs (no decay / frozen control /
        # homogeneous speeds) — a falsy-`or` default must not revert it
        from fedml_tpu.core.async_rounds import (client_durations,
                                                 durations_from_args,
                                                 merge_alpha_from_args,
                                                 staleness_fn_from_args)
        assert merge_alpha_from_args(Arguments(async_alpha=0.0)) == 0.0
        fn = staleness_fn_from_args(Arguments(async_staleness_poly=0.0))
        assert np.all(fn(np.arange(10)) == 1.0)  # a=0: no decay
        hinge = staleness_fn_from_args(Arguments(
            async_staleness_weighting="hinge", async_hinge_b=0))
        assert hinge(1) < 1.0  # b=0: decay from the first stale version
        np.testing.assert_array_equal(
            durations_from_args(4, Arguments(async_duration_sigma=0.0)),
            client_durations(4, random_seed=0, sigma=0.0))

    def test_adaptive_cap_tracks_latency_over_pour_interval(self):
        assert adaptive_staleness_cap([10.0], 1.0) == 11
        assert adaptive_staleness_cap([3.0, 30.0], 2.0) == 16
        # clipped to [lo, hi]; unobserved -> hi (no evidence, no clamp)
        assert adaptive_staleness_cap([0.1], 10.0) == 2
        assert adaptive_staleness_cap([1e9], 0.001) == 64
        assert adaptive_staleness_cap([], 1.0) == 64
        assert adaptive_staleness_cap([5.0], 0.0) == 64


# --- the update buffer --------------------------------------------------------

class TestUpdateBuffer:
    def test_pour_order_is_arrival_order_with_seq_tiebreak(self):
        buf = UpdateBuffer(3)
        buf.add(0, "a", 1.0, version=0, arrival_t=5.0)
        buf.add(1, "b", 1.0, version=0, arrival_t=1.0)
        buf.add(2, "c", 1.0, version=0, arrival_t=5.0)  # same t as "a"
        assert buf.ready()
        got = buf.pour(current_version=2)
        assert [e.update for e in got] == ["b", "a", "c"]
        assert [e.staleness(2) for e in got] == [2, 2, 2]

    def test_seeded_arrival_order_pours_deterministically(self):
        def run_once():
            rng = np.random.default_rng(42)
            events = [(float(t), i) for i, t in
                      enumerate(rng.exponential(1.0, size=20))]
            heapq.heapify(events)
            buf = UpdateBuffer(4)
            poured = []
            v = 0
            while events:
                t, cid = heapq.heappop(events)
                buf.add(cid, cid, 1.0, version=v, arrival_t=t)
                if buf.ready():
                    poured.append([e.client_id for e in buf.pour(v)])
                    v += 1
            return poured

        assert run_once() == run_once()

    def test_counters_balance(self):
        buf = UpdateBuffer(2)
        for i in range(5):
            buf.add(i, i, 1.0, version=0, arrival_t=float(i))
        buf.pour(1)
        c = buf.counters
        assert c["added"] == 5 and c["poured"] == 2 and c["buffered"] == 3
        assert c["added"] == c["poured"] + c["buffered"]

    def test_state_roundtrip_including_empty(self):
        buf = UpdateBuffer(2)
        buf.add(3, np.asarray([1.0, 2.0], np.float32), 2.5, version=1,
                arrival_t=0.7)
        st = buf.state_dict(encode=np.asarray, vec_dim=2)
        buf2 = UpdateBuffer(2)
        buf2.load_state_dict(st, decode=np.asarray)
        (e,) = buf2.pour(3, max_n=1)
        assert (e.client_id, e.weight, e.version) == (3, 2.5, 1)
        assert e.staleness(3) == 2
        np.testing.assert_array_equal(e.update, [1.0, 2.0])
        # empty buffer still snapshots at the template shape
        empty = UpdateBuffer(2).state_dict(encode=np.asarray, vec_dim=2)
        assert empty["mat"].shape == st["mat"].shape == (4, 2)

    def test_durations_are_seed_deterministic_and_heterogeneous(self):
        d1 = client_durations(16, random_seed=5)
        d2 = client_durations(16, random_seed=5)
        d3 = client_durations(16, random_seed=6)
        np.testing.assert_array_equal(d1, d2)
        assert not np.array_equal(d1, d3)
        assert np.all(d1 > 1.0) and np.std(d1) > 0

    def test_buffer_k_validation(self):
        args = Arguments(async_buffer_k=0, client_num_per_round=8)
        assert buffer_k_from_args(args, 8) == 4
        with pytest.raises(ValueError):
            buffer_k_from_args(Arguments(async_buffer_k=9), 8)


# --- the async TPU engine -----------------------------------------------------

class TestAsyncEngine:
    def test_learns_and_reports_staleness(self):
        args = sim_args(round_mode="async_buffered", comm_round=20,
                        frequency_of_the_test=20)
        sim = build_async_sim(args)
        r = sim.run()
        assert r["rounds"] == 20
        assert r["final_test_acc"] > 0.5, r["history"][-1]
        assert r["virtual_time_s"] > 0
        assert r["updates_aggregated"] == 20 * sim.k
        # heterogeneous durations guarantee genuine staleness occurred
        assert any(h["staleness_mean"] > 0 for h in sim.history)
        pours = sim.chaos_ledger.pours()
        assert len(pours) == 20
        arr = pours[-1]["injected"]["arrivals"]
        assert {"client", "staleness", "arrival_t",
                "dispatch_version"} <= set(arr[0])

    def test_pour_program_compiles_exactly_once(self, xla_compile_counter):
        args = sim_args(round_mode="async_buffered")
        sim = build_async_sim(args)
        hyper = hyper_for(args)
        sim._bootstrap(hyper)
        for _ in range(3):
            sim._pour_step(hyper)
        assert sim.dispatch_stats["compiles"] == 1  # ONE async program
        xla_compile_counter.reset()
        for _ in range(5):
            sim._pour_step(hyper)
        assert xla_compile_counter.delta() == 0
        assert sim.dispatch_stats["compiles"] == 1

    def test_sync_round_mode_is_bit_identical(self):
        from tests.test_robust_fused import build_sim  # the sync engine
        r_default = build_sim(sim_args())
        r_explicit = build_sim(sim_args(round_mode="sync"))
        hyper = hyper_for(sim_args())
        r_default.run_rounds_fused(0, 4, hyper)
        r_explicit.run_rounds_fused(0, 4, hyper)
        for a, b in zip(jax.tree_util.tree_leaves(r_default.params),
                        jax.tree_util.tree_leaves(r_explicit.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_refuses_unsupported_configs_loudly(self):
        # ISSUE 7 lifted the defense refusal (defended pours) — what
        # stays refused: DP, noise-adding defenses (DP by another name),
        # contribution assessment, and the host defense kernels
        with pytest.raises(ValueError, match="async_buffered"):
            build_async_sim(sim_args(round_mode="async_buffered",
                                     enable_dp=True, dp_epsilon=1.0,
                                     dp_delta=1e-5, dp_clip=1.0))
        with pytest.raises(ValueError, match="weak_dp"):
            build_async_sim(sim_args(round_mode="async_buffered",
                                     enable_defense=True,
                                     defense_type="weak_dp"))
        with pytest.raises(ValueError, match="contribution"):
            build_async_sim(sim_args(round_mode="async_buffered",
                                     contribution_method="loo"))
        with pytest.raises(ValueError, match="sharded"):
            build_async_sim(sim_args(round_mode="async_buffered",
                                     enable_defense=True,
                                     defense_type="krum",
                                     byzantine_client_num=1,
                                     sharded_defense="false"))
        with pytest.raises(ValueError, match="robust_fused"):
            build_async_sim(sim_args(round_mode="async_buffered",
                                     enable_defense=True,
                                     defense_type="krum",
                                     byzantine_client_num=1,
                                     robust_fused="host"))
        # the base engine refuses to silently run sync under the knob
        from tests.test_robust_fused import build_sim
        with pytest.raises(ValueError, match="AsyncBufferedSimulator"):
            build_sim(sim_args(round_mode="async_buffered"))
        with pytest.raises(ValueError, match="round_mode"):
            round_mode_from_args(Arguments(round_mode="asynch"))

    def test_runner_dispatches_on_round_mode(self):
        import fedml_tpu
        from fedml_tpu.simulation.tpu.async_engine import \
            AsyncBufferedSimulator
        from fedml_tpu.runner import FedMLRunner
        args = sim_args(round_mode="async_buffered", comm_round=2)
        from fedml_tpu import data as data_mod, model as model_mod
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        runner = FedMLRunner(args, dataset=fed, model=bundle)
        assert isinstance(runner.runner, AsyncBufferedSimulator)
        with pytest.raises(ValueError, match="Async_FedAvg"):
            FedMLRunner(sim_args(round_mode="async_buffered", backend="sp"),
                        dataset=fed, model=bundle)

    def test_chaos_rides_arrivals(self):
        args = sim_args(round_mode="async_buffered", comm_round=12,
                        chaos_dropout_prob=0.2, chaos_straggler_prob=0.3,
                        chaos_straggler_work=0.5, chaos_seed=11)
        sim = build_async_sim(args)
        r = sim.run()
        assert r["rounds"] == 12
        # stragglers take longer, so the virtual clock outruns the
        # fault-free run's
        base = build_async_sim(sim_args(round_mode="async_buffered",
                                        comm_round=12))
        rb = base.run()
        assert r["virtual_time_s"] > rb["virtual_time_s"]

    def test_adaptive_staleness_cap_engages(self):
        args = sim_args(round_mode="async_buffered", comm_round=10,
                        async_staleness_cap=0)
        sim = build_async_sim(args)
        assert sim._cap_adaptive
        sim.run()
        assert 2 <= sim.staleness_cap <= 64

    def test_bootstrap_pour_leaves_server_state_untouched(self):
        # the bootstrap dispatch pours nothing: params AND server state
        # must be bit-identical after it — FedOpt's adam would otherwise
        # advance its step count / decay moments on a zero pseudo-gradient
        args = sim_args(round_mode="async_buffered",
                        federated_optimizer="FedOpt",
                        server_optimizer="adam", server_lr=0.05)
        sim = build_async_sim(args)
        before_p = jax.device_get(sim.params)
        before_s = jax.device_get(sim.server_state)
        sim._bootstrap(hyper_for(args))
        for a, b in zip(jax.tree_util.tree_leaves(before_p),
                        jax.tree_util.tree_leaves(
                            jax.device_get(sim.params))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(before_s),
                        jax.tree_util.tree_leaves(
                            jax.device_get(sim.server_state))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_crash_resume_matches_uninterrupted(self, tmp_path):
        from fedml_tpu.core.chaos import ChaosCrash
        kw = dict(round_mode="async_buffered", comm_round=12,
                  chaos_straggler_prob=0.2, chaos_straggler_work=0.5,
                  chaos_seed=13)
        # uninterrupted reference
        ref = build_async_sim(sim_args(**kw))
        r_ref = ref.run()
        # crashed run: checkpoint every 5 pours, crash after pour 7
        ck = dict(kw, checkpoint_dir=str(tmp_path / "ck"),
                  checkpoint_every_rounds=5, chaos_crash_at_round=7)
        crash = build_async_sim(sim_args(**ck))
        with pytest.raises(ChaosCrash):
            crash.run()
        # resume: a FRESH engine restores pour 4's state (buffer,
        # in-flight events, virtual clock) and must replay pours 5..11
        # exactly as the uninterrupted run did
        resumed = build_async_sim(sim_args(**dict(
            ck, chaos_crash_at_round=None)))
        r_res = resumed.run()
        assert resumed.version == 12
        assert r_res["rounds"] == r_ref["rounds"]
        assert r_res["virtual_time_s"] == pytest.approx(
            r_ref["virtual_time_s"])
        # trajectory equality, not bit equality: the resumed engine is a
        # SEPARATELY COMPILED program instance, and XLA:CPU fuses/orders
        # reductions differently under concurrent compilation load —
        # observed drift is ~2e-5 relative over the 7 post-restore pours
        # (flaky ~1/3 of triple-suite runs at the old rtol=1e-6, flagged
        # in PR 13). The replay CLAIM (same pours, same cohorts, same
        # virtual clock) is pinned exactly above; params get a tolerance
        # with headroom over the observed drift.
        for a, b in zip(jax.tree_util.tree_leaves(r_ref["params"]),
                        jax.tree_util.tree_leaves(r_res["params"])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)


# --- async optimizers: staleness corrections ---------------------------------

class TestAsyncServerTransforms:
    def test_fedopt_damps_the_step_not_the_gradient(self):
        args = sim_args(federated_optimizer="FedOpt",
                        server_optimizer="adam", server_lr=0.1)
        from fedml_tpu.core.algframe.client_trainer import \
            ClassificationTrainer
        from fedml_tpu.optimizers.registry import create_optimizer
        opt = create_optimizer(args, ClassificationTrainer(lambda p, x: x))
        params = {"w": jnp.ones((4,))}
        state = opt.server_init(params)
        upd = {"w": jnp.full((4,), 0.5)}
        full, _ = opt.server_update_async(params, state, upd, {},
                                          jnp.int32(0), jnp.float32(1.0),
                                          jnp.float32(0.5))
        damped, _ = opt.server_update_async(params, state, upd, {},
                                            jnp.int32(0), jnp.float32(0.25),
                                            jnp.float32(0.5))
        step_full = np.asarray(full["w"]) - 1.0
        step_damped = np.asarray(damped["w"]) - 1.0
        # adam normalizes gradient scale away: the damped pour must move
        # the params by ~merge_scale times the full step
        np.testing.assert_allclose(step_damped, 0.25 * step_full,
                                   rtol=1e-5)

    def test_scaffold_control_variate_uses_pour_fraction(self):
        args = sim_args(federated_optimizer="SCAFFOLD")
        from fedml_tpu.core.algframe.client_trainer import \
            ClassificationTrainer
        from fedml_tpu.optimizers.registry import create_optimizer
        opt = create_optimizer(args, ClassificationTrainer(lambda p, x: x))
        params = {"w": jnp.zeros((3,))}
        state = opt.server_init(params)
        upd = {"w": jnp.ones((3,))}
        extras = {"delta_c": {"w": jnp.ones((3,))}}
        new_p, new_s = opt.server_update_async(
            params, state, upd, extras, jnp.int32(0), jnp.float32(0.5),
            jnp.float32(0.25))
        np.testing.assert_allclose(np.asarray(new_p["w"]), 0.5)
        # c += pour_frac * merge_scale * delta_c = 0.25 * 0.5
        np.testing.assert_allclose(np.asarray(new_s["c"]["w"]), 0.125)


# --- cross-silo async aggregator (unit level) --------------------------------

class TestAsyncAggregator:
    def _agg(self, **kw):
        from fedml_tpu.cross_silo.server.async_server import \
            AsyncFedMLAggregator
        args = Arguments(client_num_per_round=4, round_mode="async_buffered",
                         async_buffer_k=2, async_alpha=1.0,
                         async_staleness_weighting="polynomial",
                         async_staleness_poly=1.0, async_staleness_cap=4,
                         **kw)
        return AsyncFedMLAggregator(args, {"w": np.zeros((2,), np.float32)})

    def test_pour_is_staleness_weighted_delta_average(self):
        agg = self._agg()
        # two fresh uploads at version 0: plain weighted average, alpha=1
        agg.add_async_upload(1, {"w": np.asarray([1.0, 0.0], np.float32)},
                             1.0, up_version=0, arrival_t=0.0,
                             compressed=False)
        agg.add_async_upload(2, {"w": np.asarray([0.0, 1.0], np.float32)},
                             3.0, up_version=0, arrival_t=1.0,
                             compressed=False)
        arrivals = agg.pour()
        assert agg.version == 1
        assert [a["staleness"] for a in arrivals] == [0, 0]
        np.testing.assert_allclose(np.asarray(agg.global_params["w"]),
                                   [0.25, 0.75])
        # now a STALE upload from version 0 (staleness 1, weight 1/2)
        # next to a fresh one: delta formed against the version-0 base
        agg.add_async_upload(3, {"w": np.asarray([1.25, 0.75], np.float32)},
                             1.0, up_version=0, arrival_t=2.0,
                             compressed=False)  # delta vs v0 = (1.25, .75)
        agg.add_async_upload(1, {"w": np.asarray([1.25, 0.75], np.float32)},
                             1.0, up_version=1, arrival_t=3.0,
                             compressed=False)  # delta vs v1 = (1.0, 0.0)
        arrivals = agg.pour()
        assert [a["staleness"] for a in arrivals] == [1, 0]
        s = 0.5  # (1 + staleness)^-1
        exp_mix = (s * np.asarray([1.25, 0.75]) + 1.0 * np.asarray(
            [1.0, 0.0])) / (s + 1.0)
        exp_scale = (s + 1.0) / 2.0  # alpha * sum(w s)/sum(w)
        np.testing.assert_allclose(
            np.asarray(agg.global_params["w"]),
            np.asarray([0.25, 0.75]) + exp_scale * exp_mix, rtol=1e-6)

    def test_base_ring_prunes_and_falls_back_to_oldest(self, caplog):
        agg = self._agg()
        for v in range(8):  # 8 pours; cap 4 bounds the ring
            agg.add_async_upload(1, {"w": np.zeros(2, np.float32)}, 1.0,
                                 up_version=v, arrival_t=float(v),
                                 compressed=False)
            agg.add_async_upload(2, {"w": np.zeros(2, np.float32)}, 1.0,
                                 up_version=v, arrival_t=v + 0.5,
                                 compressed=False)
            agg.pour()
        assert agg.version == 8
        assert min(agg._base_ring) >= 8 - 4
        with caplog.at_level("WARNING"):
            base = agg.base_for(0)  # evicted: oldest retained, loudly
        np.testing.assert_array_equal(base,
                                      agg._base_ring[min(agg._base_ring)])
        assert any("base ring" in r.message for r in caplog.records)

    def test_refuses_dp_but_composes_with_defenses(self):
        # ISSUE 7: defenses now compose (defended pours) — only DP (and
        # the noise-adding weak_dp/crfl defenses) stay refused
        agg = self._agg(enable_defense=True, defense_type="krum",
                        byzantine_client_num=1)
        assert agg.defender.is_defense_enabled()
        with pytest.raises(ValueError, match="async_buffered"):
            self._agg(enable_dp=True, dp_epsilon=1.0, dp_delta=1e-5,
                      dp_clip=1.0)
        with pytest.raises(ValueError, match="noise-adding"):
            self._agg(enable_defense=True, defense_type="weak_dp")

    def test_pour_timeout_never_bottoms_out_at_zero(self):
        """With neither timeout knob set the liveness valve must still
        arm: K crashed silos would otherwise hang the session forever."""
        import threading
        from fedml_tpu import data as data_mod, model as model_mod
        from fedml_tpu.core.distributed.communication.inproc import \
            InProcBroker
        from fedml_tpu.cross_silo.horizontal.runner import build_server
        args = Arguments(dataset="synthetic_mnist", model="lr",
                         client_num_in_total=4, client_num_per_round=4,
                         comm_round=4, training_type="cross_silo",
                         round_mode="async_buffered")
        args.inproc_broker = InProcBroker()
        fed, output_dim = data_mod.load(args)
        server = build_server(args, fed,
                              model_mod.create(args, output_dim),
                              backend="INPROC")
        assert server.pour_timeout_s == server.DEFAULT_POUR_TIMEOUT_S
        args2 = Arguments(dataset="synthetic_mnist", model="lr",
                          client_num_in_total=4, client_num_per_round=4,
                          comm_round=4, training_type="cross_silo",
                          round_mode="async_buffered", round_timeout_s=7.0)
        args2.inproc_broker = InProcBroker()
        server2 = build_server(args2, fed,
                               model_mod.create(args2, output_dim),
                               backend="INPROC")
        assert server2.pour_timeout_s == 7.0


# --- retry budget deadline (backoff satellite) -------------------------------

class TestRetryDeadline:
    def test_deadline_caps_total_elapsed_not_just_attempts(self):
        from fedml_tpu.core.distributed.communication.backoff import \
            retry_with_backoff
        calls = []

        def slow_fail():
            calls.append(time.monotonic())
            time.sleep(0.03)  # time spent INSIDE fn counts too
            raise OSError("down")

        t0 = time.monotonic()
        with pytest.raises(OSError):
            retry_with_backoff(slow_fail, max_attempts=100, base_s=0.001,
                               max_s=0.005, deadline_s=0.1, seed=0)
        elapsed = time.monotonic() - t0
        assert elapsed < 1.0          # nowhere near 100 attempts' worth
        assert 1 <= len(calls) <= 6   # the budget cut it off early

    def test_policy_wires_the_deadline_knob(self):
        from fedml_tpu.core.distributed.communication.backoff import \
            retry_policy_from_args
        assert retry_policy_from_args(Arguments())["deadline_s"] is None
        pol = retry_policy_from_args(
            Arguments(comm_retry_deadline_s=7.5))
        assert pol["deadline_s"] == 7.5
        # and the dict feeds retry_with_backoff verbatim
        from fedml_tpu.core.distributed.communication.backoff import \
            retry_with_backoff
        with pytest.raises(OSError):
            retry_with_backoff(lambda: (_ for _ in ()).throw(OSError()),
                               retry_on=(OSError,), **dict(pol,
                                                           max_attempts=0))


# --- selection store: arrival-rate posterior ---------------------------------

class TestArrivalPosterior:
    def test_record_and_predict(self):
        from fedml_tpu.core.selection import ClientStatsStore
        st = ClientStatsStore(4)
        for gap in (2.0, 2.0, 2.0):
            st.record_arrival(1, gap)
        st.record_arrival(2, 8.0)
        rate = st.arrival_rate()
        assert rate[1] == pytest.approx(0.5)
        assert rate[0] == 0.0  # never observed: no rate, not infinite
        pred = st.predicted_staleness(pour_interval_s=2.0)
        assert pred[1] == pytest.approx(1.0)
        assert pred[2] == pytest.approx(4.0)
        assert np.isnan(pred[0])

    def test_checkpoint_tolerates_pre_async_state(self):
        from fedml_tpu.core.selection import ClientStatsStore
        st = ClientStatsStore(4)
        st.record_arrival(1, 2.0)
        old = {k: v for k, v in st.state_dict().items()
               if k not in ("ema_interarrival", "arr_obs")}
        st2 = ClientStatsStore(4)
        st2.load_state_dict(old)  # pre-async checkpoint: resumes cold
        assert np.all(st2.arr_obs == 0)


# --- in-proc async WAN session + chaos soak (slow) ---------------------------

def _run_async_session(args, n_clients, timeout_s):
    import threading
    from fedml_tpu import data as data_mod, model as model_mod
    from fedml_tpu.core.distributed.communication.inproc import InProcBroker
    from fedml_tpu.cross_silo.horizontal.runner import (build_client,
                                                        build_server)
    broker = InProcBroker()
    args.inproc_broker = broker
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    server = build_server(args, fed, bundle, backend="INPROC")
    clients = [build_client(args, fed, bundle, rank=r, backend="INPROC")
               for r in range(1, n_clients + 1)]
    for c in clients:
        threading.Thread(target=c.run, daemon=True).start()
    done = {}

    def run_server():
        server.run()
        done["ok"] = True

    st = threading.Thread(target=run_server, daemon=True)
    st.start()
    st.join(timeout=timeout_s)
    assert done.get("ok"), "async session stalled"
    return server


@pytest.mark.slow
def test_async_inproc_session_learns():
    from fedml_tpu.cross_silo.server.async_server import \
        AsyncFedMLServerManager
    args = Arguments(dataset="synthetic_mnist", model="lr",
                     client_num_in_total=4, client_num_per_round=4,
                     comm_round=12, epochs=1, batch_size=32,
                     learning_rate=0.1, frequency_of_the_test=3,
                     random_seed=9, training_type="cross_silo",
                     round_mode="async_buffered", async_pour_timeout_s=20.0)
    server = _run_async_session(args, 4, timeout_s=240.0)
    assert isinstance(server, AsyncFedMLServerManager)
    assert len(server.result["history"]) == 12
    assert server.result["final_test_acc"] > 0.6
    # staleness-tagged arrivals were recorded at aggregation time
    pours = server.chaos_ledger.pours()
    assert len(pours) == 12
    assert all("arrivals" in p["injected"] for p in pours)


@pytest.mark.slow
@pytest.mark.chaos
def test_async_chaos_soak_200_pours_no_deadlock():
    """The async server under dropout + straggler + link faults for 200
    pours: the pour loop (buffer trigger + partial-pour timeout + empty-
    fire re-sync nudge) must never deadlock, and the buffer ledger must
    balance — every arrival poured exactly once or still buffered."""
    args = Arguments(dataset="synthetic_mnist", model="lr",
                     client_num_in_total=4, client_num_per_round=4,
                     comm_round=200, epochs=1, batch_size=32,
                     learning_rate=0.05, frequency_of_the_test=50,
                     random_seed=9, training_type="cross_silo",
                     round_mode="async_buffered", async_buffer_k=2,
                     async_pour_timeout_s=3.0,
                     chaos_dropout_prob=0.2, chaos_straggler_prob=0.2,
                     chaos_straggler_work=0.5, chaos_link_loss_prob=0.05,
                     chaos_link_dup_prob=0.05, chaos_seed=23)
    server = _run_async_session(args, 4, timeout_s=540.0)
    assert len(server.result["history"]) == 200
    c = server.aggregator.buffer.counters
    assert c["added"] == c["poured"] + c["buffered"], c
    pours = server.chaos_ledger.pours()
    assert len(pours) == 200
    assert sum(p["observed"]["poured"] for p in pours) == c["poured"]
    # staleness genuinely spread under faults
    stal = [a["staleness"] for p in pours
            for a in p["injected"]["arrivals"]]
    assert max(stal) >= 1
