"""SecAgg WAN runtime: masked aggregation over the full message FSM must
match plain cross-silo FedAvg up to quantization error, without the server
ever seeing a plaintext update."""

import jax
import numpy as np
import pytest

pytest.importorskip(
    "cryptography",
    reason="core/mpc/channels.py needs the cryptography package (not"
           " bundled in every runtime image)")

from fedml_tpu import data as data_mod
from fedml_tpu import model as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_silo.horizontal.runner import run_cross_silo_inproc
from fedml_tpu.cross_silo.secagg import run_secagg_inproc

pytestmark = __import__('pytest').mark.slow


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=4, client_num_per_round=4,
                comm_round=3, epochs=1, batch_size=32, learning_rate=0.1,
                random_seed=13, training_type="cross_silo")
    base.update(kw)
    return Arguments(**base)


def test_secagg_session_learns_and_matches_plain():
    args = make_args()
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    result = run_secagg_inproc(args, fed, bundle)
    assert result is not None
    assert result["final_test_acc"] > 0.6, result["history"]

    args2 = make_args()
    fed2, output_dim2 = data_mod.load(args2)
    bundle2 = model_mod.create(args2, output_dim2)
    plain = run_cross_silo_inproc(args2, fed2, bundle2)
    # quantization at 2^-16 over 3 rounds: tolerances well above that
    for a, b in zip(jax.tree_util.tree_leaves(plain["params"]),
                    jax.tree_util.tree_leaves(result["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_secagg_dropout_recovery():
    """One silo dies after key setup and never submits a masked model. The
    server must time out, proceed with the >= threshold survivors,
    reconstruct the dropped client's pairwise masks from Shamir shares, and
    produce EXACTLY the survivors-only weighted aggregate (up to
    quantization) — a wrongly-unmasked sum would be garbage, not close."""
    from fedml_tpu.cross_silo.secagg import (SecAggClientManager,
                                             run_secagg_inproc)
    from fedml_tpu.cross_silo.horizontal.runner import _build_spec
    from fedml_tpu.cross_silo.client.trainer import SiloTrainer
    from fedml_tpu.optimizers.registry import create_optimizer

    DROP_RANK = 4  # client idx 3

    class DroppingClient(SecAggClientManager):
        def on_train(self, msg):
            return  # dead silo: participated in setup, never trains

    args = make_args(comm_round=2, round_timeout_s=10.0)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)

    def factory(rank, a, trainer):
        cls = DroppingClient if rank == DROP_RANK else SecAggClientManager
        return cls(a, trainer, rank=rank, size=5, backend="INPROC")

    result = run_secagg_inproc(args, fed, bundle, client_factory=factory)
    assert result is not None and "error" not in result, result
    assert len(result["history"]) == 2

    # expected: plain weighted FedAvg over survivors 0..2 only
    args2 = make_args(comm_round=2)
    fed2, output_dim2 = data_mod.load(args2)
    bundle2 = model_mod.create(args2, output_dim2)
    spec = _build_spec(fed2, bundle2, None)
    rng = jax.random.PRNGKey(int(args2.random_seed))
    init_rng, _ = jax.random.split(rng)
    params = bundle2.init(init_rng, fed2.train.x[0, 0])
    trainers = []
    for _ in range(3):
        opt = create_optimizer(args2, spec)
        trainers.append(SiloTrainer(args2, fed2, bundle2, spec, opt))
    for r in range(2):
        deltas, ws = [], []
        for idx in range(3):
            new_p, n, _ = trainers[idx].train(params, idx, r)
            deltas.append(jax.tree_util.tree_map(
                lambda a, b: np.asarray(a) - np.asarray(b), new_p, params))
            ws.append(n)
        wsum = sum(ws)
        agg = jax.tree_util.tree_map(
            lambda *ds: sum(w * d for w, d in zip(ws, ds)) / wsum, *deltas)
        params = jax.tree_util.tree_map(
            lambda p, u: np.asarray(p) + u, params, agg)

    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(result["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_client_refuses_active_server_unmask_attack():
    """A deviating server listing a client as BOTH surviving and dropped
    would collect that client's self-mask seed AND mask key — enough to
    strip both masks and recover its individual update (ADVICE r3 medium).
    The client must refuse. Cross-round replays get nothing either: each
    round has fresh secrets, and the client answers once then wipes."""
    from fedml_tpu.core.distributed.communication.inproc import InProcBroker
    from fedml_tpu.core.distributed.communication.message import Message
    from fedml_tpu.cross_silo.secagg import SAMessage, SecAggClientManager

    args = make_args()
    args.inproc_broker = InProcBroker()
    c = SecAggClientManager(args, trainer=None, rank=1, size=5,
                            backend="INPROC")
    c._round = {"round": 0,
                "held": {i: ([[1, 2]] * 6, [[1, 2]] * 11) for i in range(4)}}
    sent = []
    c.send_message = sent.append
    c.finish = lambda: None

    # same index in both lists -> refuse outright
    msg = Message(SAMessage.S2C_UNMASK_REQUEST, 0, 1)
    msg.add_params(SAMessage.KEY_ROUND, 0)
    msg.add_params(SAMessage.KEY_SURVIVING, [0, 1, 2])
    msg.add_params(SAMessage.KEY_DROPPED, [2, 3])
    c.on_unmask_request(msg)
    assert sent == [], "client revealed shares under an overlapping request"

    # legitimate request for round 0 -> answered once
    c._round = {"round": 0,
                "held": {i: ([[1, 2]] * 6, [[1, 2]] * 11) for i in range(4)}}
    msg = Message(SAMessage.S2C_UNMASK_REQUEST, 0, 1)
    msg.add_params(SAMessage.KEY_ROUND, 0)
    msg.add_params(SAMessage.KEY_SURVIVING, [0, 1, 2])
    msg.add_params(SAMessage.KEY_DROPPED, [3])
    c.on_unmask_request(msg)
    assert len(sent) == 1
    # secrets are wiped after the answer — a replayed/altered request for
    # the same round reveals nothing
    assert c._round is None
    c._round = {"round": 0,
                "held": {i: ([[1, 2]] * 6, [[1, 2]] * 11) for i in range(4)}}
    msg = Message(SAMessage.S2C_UNMASK_REQUEST, 0, 1)
    msg.add_params(SAMessage.KEY_ROUND, 0)
    msg.add_params(SAMessage.KEY_SURVIVING, [0, 1])
    msg.add_params(SAMessage.KEY_DROPPED, [2])
    c.on_unmask_request(msg)
    assert len(sent) == 1, "client answered the same round twice"


def test_secagg_dropout_after_shares_reconstructs_masks():
    """A silo that completes key+share distribution but never submits its
    masked model is in the mask cohort: survivors' masked vectors carry
    pairwise masks with it. The server must reconstruct its mask key from
    Shamir shares, cancel the residual masks, and produce EXACTLY the
    survivors-only aggregate — this is the Bonawitz recovery path proper."""
    from fedml_tpu.cross_silo.secagg import (SecAggClientManager,
                                             run_secagg_inproc)

    DROP_RANK = 2  # client idx 1

    class DropAfterShares(SecAggClientManager):
        def on_routed_shares(self, msg):
            return  # dies between share distribution and masking

    args = make_args(comm_round=1, round_timeout_s=10.0)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)

    def factory(rank, a, trainer):
        cls = DropAfterShares if rank == DROP_RANK else SecAggClientManager
        return cls(a, trainer, rank=rank, size=5, backend="INPROC")

    result = run_secagg_inproc(args, fed, bundle, client_factory=factory)
    assert result is not None and "error" not in result, result
    assert len(result["history"]) == 1

    # expected: plain weighted FedAvg over survivors 0, 2, 3 only
    from fedml_tpu.cross_silo.horizontal.runner import _build_spec
    from fedml_tpu.cross_silo.client.trainer import SiloTrainer
    from fedml_tpu.optimizers.registry import create_optimizer
    args2 = make_args(comm_round=1)
    fed2, output_dim2 = data_mod.load(args2)
    bundle2 = model_mod.create(args2, output_dim2)
    spec = _build_spec(fed2, bundle2, None)
    rng = jax.random.PRNGKey(int(args2.random_seed))
    init_rng, _ = jax.random.split(rng)
    params = bundle2.init(init_rng, fed2.train.x[0, 0])
    deltas, ws = [], []
    for idx in [0, 2, 3]:
        opt = create_optimizer(args2, spec)
        tr = SiloTrainer(args2, fed2, bundle2, spec, opt)
        new_p, n, _ = tr.train(params, idx, 0)
        deltas.append(jax.tree_util.tree_map(
            lambda a, b: np.asarray(a) - np.asarray(b), new_p, params))
        ws.append(n)
    wsum = sum(ws)
    agg = jax.tree_util.tree_map(
        lambda *ds: sum(w * d for w, d in zip(ws, ds)) / wsum, *deltas)
    expect = jax.tree_util.tree_map(
        lambda p, u: np.asarray(p) + u, params, agg)

    for a, b in zip(jax.tree_util.tree_leaves(expect),
                    jax.tree_util.tree_leaves(result["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_secagg_straggler_rejoins_next_round():
    """A client that misses round 0's key-advertisement deadline is left
    out of that round's cohort — and REJOINS round 1 with fresh keys (the
    per-round protocol makes round membership elastic, not a session
    death sentence)."""
    from fedml_tpu.cross_silo.secagg import (SecAggClientManager,
                                             run_secagg_inproc)

    SLOW_RANK = 3  # client idx 2
    rejoined_rounds = []

    class SlowFirstRound(SecAggClientManager):
        def on_train(self, msg):
            if int(msg.get("round", 0)) == 0:
                return  # missed the round-0 deadline entirely
            rejoined_rounds.append(int(msg.get("round")))
            super().on_train(msg)

    args = make_args(comm_round=2, round_timeout_s=10.0)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)

    def factory(rank, a, trainer):
        cls = SlowFirstRound if rank == SLOW_RANK else SecAggClientManager
        return cls(a, trainer, rank=rank, size=5, backend="INPROC")

    result = run_secagg_inproc(args, fed, bundle, client_factory=factory)
    assert result is not None and "error" not in result, result
    assert len(result["history"]) == 2
    # the straggler actually participated in round 1 (got the TRAIN
    # message and ran the full key/share/mask path), not merely "the
    # survivors finished without it"
    assert rejoined_rounds == [1], rejoined_rounds
    # both rounds aggregated and the model learned
    assert result["final_test_acc"] > 0.4, result["history"]


def test_server_relays_only_ciphertext():
    """What the server sees of the routed shares must be AEAD ciphertext it
    cannot open: no plaintext share bytes, and decryption without the
    recipient's channel key fails authentication."""
    import msgpack
    import pytest
    from fedml_tpu.core.mpc import channels
    from fedml_tpu.cross_silo.secagg import SAMessage, SecAggServerManager

    seen = {}

    class SpyServer(SecAggServerManager):
        def on_shares(self, msg):
            owner = msg.get_sender_id() - 1
            seen[owner] = dict(msg.get(SAMessage.KEY_SHARES))
            super().on_shares(msg)

    import fedml_tpu.cross_silo.secagg as sa_mod
    orig = sa_mod.SecAggServerManager
    sa_mod.SecAggServerManager = SpyServer
    try:
        args = make_args(comm_round=1)
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        result = run_secagg_inproc(args, fed, bundle)
    finally:
        sa_mod.SecAggServerManager = orig
    assert result is not None and "error" not in result
    assert len(seen) == 4
    eve_sk, _eve_pk = channels.keygen()
    for owner, routed in seen.items():
        for j, blob in routed.items():
            blob = bytes(blob)
            # not a msgpack share list in the clear
            with pytest.raises(Exception):
                payload = msgpack.unpackb(blob)
                assert isinstance(payload, list)  # reached = plaintext leak
            # and not openable without the recipient's secret key
            with pytest.raises(channels.DecryptError):
                channels.open_sealed(
                    eve_sk, _eve_pk, blob,
                    aad=channels.pair_aad(int(owner), int(j),
                                          b"sa-round-0"))
