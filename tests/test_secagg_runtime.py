"""SecAgg WAN runtime: masked aggregation over the full message FSM must
match plain cross-silo FedAvg up to quantization error, without the server
ever seeing a plaintext update."""

import jax
import numpy as np

from fedml_tpu import data as data_mod
from fedml_tpu import model as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_silo.horizontal.runner import run_cross_silo_inproc
from fedml_tpu.cross_silo.secagg import run_secagg_inproc


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=4, client_num_per_round=4,
                comm_round=3, epochs=1, batch_size=32, learning_rate=0.1,
                random_seed=13, training_type="cross_silo")
    base.update(kw)
    return Arguments(**base)


def test_secagg_session_learns_and_matches_plain():
    args = make_args()
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    result = run_secagg_inproc(args, fed, bundle)
    assert result is not None
    assert result["final_test_acc"] > 0.6, result["history"]

    args2 = make_args()
    fed2, output_dim2 = data_mod.load(args2)
    bundle2 = model_mod.create(args2, output_dim2)
    plain = run_cross_silo_inproc(args2, fed2, bundle2)
    # quantization at 2^-16 over 3 rounds: tolerances well above that
    for a, b in zip(jax.tree_util.tree_leaves(plain["params"]),
                    jax.tree_util.tree_leaves(result["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)
