"""SplitNN and vertical FL as REAL distributed sessions (VERDICT r4 item
1): server + parties exchanging activations/contributions and gradients
as Messages over the comm stack, with numerical parity against the fused
single-process simulators on the same config."""

import numpy as np
import pytest

from fedml_tpu import data as data_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_silo.split_learning import run_splitnn_inproc
from fedml_tpu.cross_silo.vertical import run_vfl_inproc
from fedml_tpu.simulation.sp.split_nn import SplitNNSimulator
from fedml_tpu.simulation.sp.vertical_fl import VerticalFLSimulator

pytestmark = pytest.mark.slow


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _args(**kw):
    base = dict(dataset="digits", model="lr", client_num_in_total=3,
                client_num_per_round=3, comm_round=3, epochs=1,
                batch_size=32, learning_rate=0.1,
                frequency_of_the_test=1, random_seed=7,
                training_type="cross_silo")
    base.update(kw)
    return Arguments(**base)


class TestSplitNNSession:
    def test_distributed_matches_sp_simulator(self):
        """The socketed protocol is the same chain rule as the fused SP
        program: activations forward, activation-grads back, identical
        update order — accuracies must agree round for round."""
        args = _args(federated_optimizer="split_nn")
        fed, _ = data_mod.load(args)
        dist = run_splitnn_inproc(args, fed)
        sp = SplitNNSimulator(_args(federated_optimizer="split_nn"),
                              fed, None).run()
        assert dist is not None
        assert dist["rounds"] == sp["rounds"] == 3
        d_acc = [r["test_acc"] for r in dist["history"] if "test_acc" in r]
        s_acc = [r["test_acc"] for r in sp["history"] if "test_acc" in r]
        assert len(d_acc) == len(s_acc) == 3
        np.testing.assert_allclose(d_acc, s_acc, atol=0.02)
        assert dist["final_test_acc"] > 0.5

    def test_runner_dispatch_cross_silo(self):
        """federated_optimizer: split_nn under training_type: cross_silo
        builds the distributed managers (server role)."""
        from fedml_tpu.cross_silo.horizontal.runner import CrossSiloRunner
        from fedml_tpu.cross_silo.split_learning import SplitNNServerManager
        args = _args(federated_optimizer="split_nn", role="server",
                     backend="TCP", tcp_base_port=_free_port())
        fed, _ = data_mod.load(args)
        # TCP rank 0 binds a listener; construction proves the dispatch
        runner = CrossSiloRunner(args, fed, None)
        assert isinstance(runner.manager, SplitNNServerManager)
        runner.manager.com_manager.stop_receive_message()


class TestVFLSession:
    def test_distributed_matches_sp_simulator(self):
        """Only d(loss)/d(logits) crosses the boundary; the joint gradient
        factors through it, so the distributed session and the fused SP
        program are the same optimization trajectory."""
        args = _args(federated_optimizer="vfl", party_num=2)
        fed, _ = data_mod.load(args)
        dist = run_vfl_inproc(args, fed)
        sp = VerticalFLSimulator(_args(federated_optimizer="vfl",
                                       party_num=2), fed, None).run()
        assert dist is not None
        assert dist["rounds"] == sp["rounds"] == 3
        d_acc = [r["test_acc"] for r in dist["history"] if "test_acc" in r]
        s_acc = [r["test_acc"] for r in sp["history"] if "test_acc" in r]
        assert len(d_acc) == len(s_acc) == 3
        np.testing.assert_allclose(d_acc, s_acc, atol=0.02)
        assert dist["final_test_acc"] > 0.5

    def test_runner_dispatch_cross_silo(self):
        from fedml_tpu.cross_silo.horizontal.runner import CrossSiloRunner
        from fedml_tpu.cross_silo.vertical import VFLServerManager
        args = _args(federated_optimizer="vfl", party_num=2, role="server",
                     backend="TCP", tcp_base_port=_free_port())
        fed, _ = data_mod.load(args)
        runner = CrossSiloRunner(args, fed, None)
        assert isinstance(runner.manager, VFLServerManager)
        runner.manager.com_manager.stop_receive_message()
