"""Pub/sub + object-store transport (the MQTT+S3 control/data split,
reference mqtt_s3_multi_clients_comm_manager.py) and the content-addressed
storage (reference s3/remote_storage.py + distributed_storage/)."""

import threading
import time

import numpy as np
import pytest

from fedml_tpu.core.distributed.communication.base_com_manager import Observer
from fedml_tpu.core.distributed.communication.message import (Message,
                                                              tree_to_wire)
from fedml_tpu.core.distributed.communication.pubsub import (
    PubSubBroker, PubSubStorageCommManager)
from fedml_tpu.core.distributed.distributed_storage import LocalObjectStorage


class Sink(Observer):
    def __init__(self):
        self.got = threading.Event()
        self.msg = None

    def receive_message(self, msg_type, msg):
        self.msg = msg
        self.got.set()


def test_object_storage_roundtrip(tmp_path):
    store = LocalObjectStorage(str(tmp_path))
    key = store.put_object(b"hello world")
    assert key.startswith("cas://")
    assert store.get_object(key) == b"hello world"
    # model payloads
    params = {"w": np.arange(10.0, dtype=np.float32)}
    mkey = store.write_model(params)
    out = store.read_model(mkey)
    np.testing.assert_allclose(out["w"], params["w"])


def test_pubsub_offloads_large_payloads(tmp_path):
    broker = PubSubBroker()
    store = LocalObjectStorage(str(tmp_path))
    a = PubSubStorageCommManager(1, broker_port=broker.port, storage=store,
                                 offload_threshold=1024)
    b = PubSubStorageCommManager(0, broker_port=broker.port, storage=store)
    sink = Sink()
    b.add_observer(sink)
    threading.Thread(target=b.handle_receive_message, daemon=True).start()
    time.sleep(0.1)
    big = tree_to_wire({"w": np.random.RandomState(0).randn(64, 64)
                        .astype(np.float32)})
    msg = Message("model_up", 1, 0)
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, big)
    msg.add_params(Message.MSG_ARG_KEY_NUM_SAMPLES, 32.0)
    a.send_message(msg)
    assert sink.got.wait(10), "message not delivered"
    got = sink.msg
    # the wire message carried a storage KEY, and the receive path
    # re-hydrated the payload from the object store
    assert got.get(Message.MSG_ARG_KEY_MODEL_PARAMS_URL, "").startswith(
        "cas://")
    np.testing.assert_allclose(
        got.get(Message.MSG_ARG_KEY_MODEL_PARAMS)["w"], big["w"])
    a.stop_receive_message()
    b.stop_receive_message()
    broker.stop()


def test_pubsub_last_will_fires_on_dead_client(tmp_path):
    broker = PubSubBroker()
    store = LocalObjectStorage(str(tmp_path))
    server = PubSubStorageCommManager(0, broker_port=broker.port,
                                      storage=store)
    client = PubSubStorageCommManager(3, broker_port=broker.port,
                                      storage=store)
    sink = Sink()
    server.add_observer(sink)
    threading.Thread(target=server.handle_receive_message,
                     daemon=True).start()
    time.sleep(0.1)
    client._sock.close()  # HARD drop (no goodbye) -> broker fires the will
    assert sink.got.wait(10), "last-will not delivered"
    assert sink.msg.get_type() == "client_offline"
    assert sink.msg.get_sender_id() == 3
    server.stop_receive_message()
    broker.stop()


def test_pubsub_graceful_disconnect_clears_will(tmp_path):
    """MQTT LWT semantics: a clean goodbye must NOT fire the will."""
    broker = PubSubBroker()
    store = LocalObjectStorage(str(tmp_path))
    server = PubSubStorageCommManager(0, broker_port=broker.port,
                                      storage=store)
    client = PubSubStorageCommManager(4, broker_port=broker.port,
                                      storage=store)
    sink = Sink()
    server.add_observer(sink)
    threading.Thread(target=server.handle_receive_message,
                     daemon=True).start()
    time.sleep(0.1)
    client.stop_receive_message()  # graceful: disconnect frame first
    assert not sink.got.wait(1.5), "will fired on graceful disconnect"
    server.stop_receive_message()
    broker.stop()


def test_cross_silo_session_over_pubsub(tmp_path, monkeypatch):
    """Full FL session with the control/data split: server + 2 silos over
    the broker, payloads through the object store."""
    monkeypatch.setenv("FEDML_TPU_STORAGE_DIR", str(tmp_path))
    from fedml_tpu import data as data_mod
    from fedml_tpu import model as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.cross_silo.horizontal.runner import (build_client,
                                                        build_server)
    broker = PubSubBroker()
    args = Arguments(dataset="synthetic_mnist", model="lr",
                     client_num_in_total=2, client_num_per_round=2,
                     comm_round=2, epochs=1, batch_size=32,
                     learning_rate=0.1, frequency_of_the_test=1,
                     random_seed=7, training_type="cross_silo",
                     backend="PUBSUB", pubsub_broker_port=broker.port)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    server = build_server(args, fed, bundle, backend="PUBSUB")
    clients = [build_client(args, fed, bundle, rank=r, backend="PUBSUB")
               for r in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30)
    assert server.result is not None
    assert len(server.result["history"]) == 2
    assert server.result["final_test_acc"] > 0.6
    broker.stop()
