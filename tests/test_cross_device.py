"""Cross-device pillar: device protocol session (3 simulated devices),
native C++ engine parity, native masking round-trip."""

import jax
import numpy as np
import pytest

from fedml_tpu import data as data_mod
from fedml_tpu import model as model_mod
from fedml_tpu import native
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_device import run_cross_device_inproc


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=3, client_num_per_round=3,
                comm_round=3, epochs=1, batch_size=32, learning_rate=0.1,
                random_seed=3, training_type="cross_device")
    base.update(kw)
    return Arguments(**base)


def test_three_devices_complete_rounds(tmp_path):
    args = make_args(model_file_cache_dir=str(tmp_path))
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    result = run_cross_device_inproc(args, fed, bundle)
    assert result is not None
    assert len(result["history"]) == 3
    assert result["final_test_acc"] > 0.5, result["history"]


def test_native_engine_device_session(tmp_path):
    """One device trains in the C++ core, two in JAX — the server
    aggregates both interchangeably (the MobileNN story)."""
    if not native.available():
        pytest.skip("no native toolchain")
    args = make_args(model_file_cache_dir=str(tmp_path), learning_rate=0.2)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    result = run_cross_device_inproc(args, fed, bundle,
                                     engines=["native", None, None])
    assert result is not None
    assert len(result["history"]) == 3
    assert result["final_test_acc"] > 0.5, result["history"]
    # the native device evaluated each round's GLOBAL model on-device and
    # the server recorded the reported accuracy (MobileNN eval story)
    accs = [r["device_eval_acc"] for r in result["history"]
            if "device_eval_acc" in r]
    assert len(accs) == 3, result["history"]
    assert all(0.0 <= a <= 1.0 for a in accs)
    assert accs[-1] > accs[0], accs  # global model improved across rounds


class TestNativeCore:
    def test_native_trainer_learns_real_digits(self):
        if not native.available():
            pytest.skip("no native toolchain")
        from sklearn.datasets import load_digits
        ds = load_digits()
        x = (ds.data / 16.0).astype(np.float32)
        y = ds.target
        t = native.NativeLinearTrainer()
        params = {"Dense_0": {"kernel": np.zeros((64, 10), np.float32),
                              "bias": np.zeros(10, np.float32)}}
        p, loss = t.train(params, x[:1500], y[:1500], epochs=5,
                          batch_size=32, lr=0.3, seed=1)
        assert t.evaluate(p, x[1500:], y[1500:]) > 0.85
        assert loss < 0.6

    def test_native_gradient_matches_numpy(self):
        """One full-batch step of the C++ trainer equals the analytic
        softmax-regression gradient step."""
        if not native.available():
            pytest.skip("no native toolchain")
        rs = np.random.RandomState(0)
        x = rs.randn(8, 5).astype(np.float32)
        y = rs.randint(0, 3, 8).astype(np.int64)
        W0 = rs.randn(5, 3).astype(np.float32) * 0.1
        b0 = rs.randn(3).astype(np.float32) * 0.1
        lr = 0.5
        t = native.NativeLinearTrainer()
        p, _ = t.train({"Dense_0": {"kernel": W0.copy(), "bias": b0.copy()}},
                       x, y, epochs=1, batch_size=8, lr=lr, seed=0)
        # numpy reference
        logits = x @ W0 + b0
        e = np.exp(logits - logits.max(1, keepdims=True))
        probs = e / e.sum(1, keepdims=True)
        onehot = np.eye(3)[y]
        dl = (probs - onehot)
        gW = x.T @ dl / len(x)
        gb = dl.mean(0)
        np.testing.assert_allclose(p["Dense_0"]["kernel"], W0 - lr * gW,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(p["Dense_0"]["bias"], b0 - lr * gb,
                                   rtol=1e-4, atol=1e-5)

    def test_native_mask_sums_cancel(self):
        """LightSecAgg shape: sum of masked vectors minus sum of masks
        reconstructs the sum of updates (field arithmetic mod 2^31-1)."""
        if not native.available():
            pytest.skip("no native toolchain")
        scale = 65536.0
        rs = np.random.RandomState(1)
        vs = [rs.randn(500).astype(np.float32) for _ in range(3)]
        seeds = [11, 22, 33]
        masked = [native.mask_vector(v, scale, s)
                  for v, s in zip(vs, seeds)]
        p = native.PRIME
        agg = np.zeros(500, np.uint64)
        for m in masked:
            agg = (agg + m) % p
        for s in seeds:
            agg = (agg + p - native.gen_mask(500, s)) % p
        half = p // 2
        # each quantized value was offset by +half -> remove 3*half
        agg = (agg + p - (3 * half) % p) % p
        # centered lift: the summed fixed-point value is small vs p
        signed = np.where(agg > half, agg.astype(np.int64) - p,
                          agg.astype(np.int64))
        recovered = signed.astype(np.float64) / scale
        np.testing.assert_allclose(recovered, sum(vs), atol=1e-3)


def test_dead_device_does_not_stall_round(tmp_path):
    """Elastic rounds (capability beyond the reference's cross-device
    server): a device that dies after registration must not hang the
    all-received barrier — the round aggregates the reporters."""
    import threading
    from fedml_tpu.core.distributed.communication.inproc import InProcBroker
    from fedml_tpu.cross_device import (DeviceClientManager,
                                        build_device_client,
                                        build_device_server)

    class DeadDevice(DeviceClientManager):
        def handle_round(self, msg):
            self.finish()  # dies before training/uploading

    args = make_args(comm_round=2, round_timeout_s=12.0,
                     model_file_cache_dir=str(tmp_path))
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    broker = InProcBroker()
    args.inproc_broker = broker
    server = build_device_server(args, fed, bundle, backend="INPROC")
    devices = [build_device_client(args, fed, bundle, device_id=i,
                                   backend="INPROC") for i in (1, 2)]
    from fedml_tpu.core.algframe.client_trainer import make_trainer_spec
    from fedml_tpu.optimizers.registry import create_optimizer
    spec = make_trainer_spec(fed, bundle)
    dead = DeadDevice(args, fed, bundle, spec,
                      create_optimizer(args, spec), device_id=3,
                      backend="INPROC")
    threads = [threading.Thread(target=d.run, daemon=True)
               for d in devices + [dead]]
    for t in threads:
        t.start()
    done = {}

    def run_server():
        server.run()
        done["ok"] = True

    st = threading.Thread(target=run_server, daemon=True)
    st.start()
    st.join(timeout=120)
    assert done.get("ok"), "server stalled on the dead device"
    assert len(server.result["history"]) == 2
    assert server.result["final_test_acc"] > 0.5


class TestCohortAssembly:
    """Streaming cohort assembly on the cross-device scheduler path
    (ISSUE 15): eligibility predicates from the registration handshake,
    pacer-driven deadlines, and chaos (a dead cohort member) + selection
    composing on the same rounds — the standing scenario gap."""

    def _session(self, tmp_path, n_devices, dead=(), eligibility=None,
                 **kw):
        import threading
        from fedml_tpu.core.distributed.communication.inproc import \
            InProcBroker
        from fedml_tpu.cross_device import (DeviceClientManager,
                                            build_device_client,
                                            build_device_server)

        class DeadDevice(DeviceClientManager):
            def handle_round(self, msg):
                self.finish()  # dies before training/uploading

        args = make_args(model_file_cache_dir=str(tmp_path),
                         client_num_in_total=n_devices,
                         client_num_per_round=n_devices,
                         cohort_assembly=True, **kw)
        args.inproc_broker = InProcBroker()
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        server = build_device_server(args, fed, bundle, backend="INPROC")
        eligs = eligibility or [None] * n_devices
        devices = []
        for i in range(1, n_devices + 1):
            if i in dead:
                from fedml_tpu.core.algframe.client_trainer import \
                    make_trainer_spec
                from fedml_tpu.optimizers.registry import create_optimizer
                spec = make_trainer_spec(fed, bundle)
                devices.append(DeadDevice(
                    args, fed, bundle, spec, create_optimizer(args, spec),
                    device_id=i, backend="INPROC",
                    eligibility=eligs[i - 1]))
            else:
                devices.append(build_device_client(
                    args, fed, bundle, device_id=i, backend="INPROC",
                    eligibility=eligs[i - 1]))
        threads = [threading.Thread(target=d.run, daemon=True)
                   for d in devices]
        for t in threads:
            t.start()
        done = {}

        def run_server():
            server.run()
            done["ok"] = True

        st = threading.Thread(target=run_server, daemon=True)
        st.start()
        st.join(timeout=120)
        assert done.get("ok"), "server stalled"
        return server

    def test_eligibility_filters_cohort(self, tmp_path):
        """A device registering as not-charging must never be scheduled
        while cohort_require_charging is on — and rounds still close on
        the eligible cohort."""
        server = self._session(
            tmp_path, n_devices=3, comm_round=3, cohort_size=2,
            cohort_require_charging=True,
            eligibility=[None, {"charging": False}, None])
        assert len(server.result["history"]) == 3
        assert server.result["final_test_acc"] > 0.5
        # device 2 (ineligible) was never selected, never participated
        sel = server.stats.times_selected_for([1, 2, 3])
        assert sel[1] == 0 and sel[0] == 3 and sel[2] == 3
        # successful rounds (barrier k met) must NOT read as
        # under-delivery: the pacer measures against the wanted k, not
        # the over-sampled dispatch width
        assert server.pacer.deadline_s <= 60.0
        assert float(np.sum(server.stats.dropout_posterior_mean([2]))) \
            < 0.1  # no dropout evidence either — it was never asked

    def test_chaos_plus_selection_pacer_adapts(self, tmp_path):
        """A cohort member that dies post-registration (the chaos leg)
        forces deadline closes; the pacer observes the under-delivery
        and stretches the deadline — chaos + selection composing on the
        cross-device scheduler path."""
        server = self._session(
            tmp_path, n_devices=3, dead={3}, comm_round=2,
            pacer_deadline_s=2.0, pacer_target_frac=0.9)
        assert len(server.result["history"]) == 2
        assert server.result["final_test_acc"] > 0.5
        # under-delivered rounds stretched the pacer
        assert server.pacer.deadline_s > 2.0
        assert server.pacer.over_sample > 1.3
        assert server.pacer.rounds_observed == 2
        # the dead device accumulated dropout evidence; the live ones
        # accumulated participation + upload latency
        assert server.stats.dropout_posterior_mean([3])[0] > \
            server.stats.dropout_posterior_mean([1])[0]
        lat = server.stats.latency_for([1, 2])
        assert np.all(np.isfinite(lat))

    def test_reregister_is_idempotent(self, tmp_path):
        """A device re-registering under the same id (network flap, app
        restart) refreshes its handshake in place: no duplicate online
        slot, no duplicate registry row, no stats reset, no second
        session dispatch (ISSUE 18 satellite)."""
        from fedml_tpu.core.distributed.communication.inproc import \
            InProcBroker
        from fedml_tpu.core.distributed.communication.message import \
            Message
        from fedml_tpu.cross_device import build_device_server
        from fedml_tpu.cross_device.message_define import DeviceMessage

        args = make_args(model_file_cache_dir=str(tmp_path),
                         client_num_in_total=2, client_num_per_round=2,
                         cohort_assembly=True,
                         fleet_registry=str(tmp_path / "fleet.db"))
        args.inproc_broker = InProcBroker()
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        server = build_device_server(args, fed, bundle, backend="INPROC")
        server.stats.record_availability(1, participated=True)

        def reg_msg(did, charging=True):
            msg = Message(DeviceMessage.MSG_TYPE_D2S_REGISTER, did, 0)
            msg.add_params(DeviceMessage.ARG_DEVICE_ID, did)
            msg.add_params(DeviceMessage.ARG_DEVICE_OS, "test")
            msg.add_params(DeviceMessage.ARG_DEVICE_ENGINE, "jax")
            msg.add_params(DeviceMessage.ARG_DEVICE_CHARGING, charging)
            return msg

        server.handle_register(reg_msg(1, charging=True))
        server.handle_register(reg_msg(1, charging=False))  # flap
        # one online slot, refreshed in place; still waiting for dev 2
        assert len(server.devices_online) == 1
        assert server.devices_online[1]["charging"] is False
        assert not server.is_initialized
        # one registry row, counted registrations, history intact
        row = server.fleet.device(1)
        assert server.fleet.device_count() == 1
        assert row["registrations"] == 2
        assert row["charging"] is False
        # the stats evidence recorded before the flap survived
        assert float(server.stats.dropout_posterior_mean([1])[0]) < 0.5

    def test_cohort_off_is_legacy_path(self, tmp_path):
        """cohort_assembly off (default): no stats plane, every online
        device trains — the pre-PR behavior byte-for-byte."""
        args = make_args(model_file_cache_dir=str(tmp_path))
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        result = run_cross_device_inproc(args, fed, bundle)
        assert len(result["history"]) == 3
        from fedml_tpu.cross_device.runner import build_device_server
        server = build_device_server(args, fed, bundle, backend="INPROC")
        assert not server.cohort_enabled
        assert server.stats is None and server.pacer is None


def test_artifact_codec_is_not_pickle(tmp_path):
    """Model artifacts are msgpack (magic-checked), never pickled — loading
    a foreign file must fail loudly, not execute code."""
    import pickle

    from fedml_tpu.serving import load_model, save_model

    params = {"dense": {"kernel": np.ones((3, 2), np.float32),
                        "bias": np.zeros((2,), np.float32)}}
    path = str(tmp_path / "m.npk")
    save_model(params, path)
    with open(path, "rb") as f:
        blob = f.read()
    assert blob[:6] == b"FMTPU1"
    back = load_model(path)
    np.testing.assert_array_equal(back["dense"]["kernel"],
                                  params["dense"]["kernel"])
    evil = str(tmp_path / "evil.npk")
    with open(evil, "wb") as f:
        pickle.dump({"x": 1}, f)
    with pytest.raises(ValueError, match="bad magic"):
        load_model(evil)


def test_peer_path_confinement(tmp_path):
    """A peer-supplied model-file path outside the cache dir is rejected
    before it is ever opened (ADVICE r2 medium)."""
    from fedml_tpu.utils.paths import confine_path

    root = tmp_path / "cache"
    root.mkdir()
    inside = root / "ok.npk"
    inside.write_bytes(b"x")
    assert confine_path(str(inside), str(root))
    for bad in ("/etc/passwd", str(root / ".." / "escape.npk"),
                str(tmp_path / "other.npk")):
        with pytest.raises(ValueError, match="escapes"):
            confine_path(bad, str(root))


def test_dead_round_leash_zero_arrivals(tmp_path):
    """If NO device reports in a round, the 3x leash armed at dispatch
    closes the round with the previous global model (ADVICE r2)."""
    import time

    from fedml_tpu.core.distributed.communication.inproc import InProcBroker
    from fedml_tpu.cross_device.runner import build_device_server

    args = make_args(model_file_cache_dir=str(tmp_path), comm_round=2,
                     client_num_per_round=1, round_timeout_s=0.3)
    args.inproc_broker = InProcBroker()
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    server = build_device_server(args, fed, bundle, backend="INPROC")
    server.send_message = lambda msg: None   # devices never hear dispatch
    server.finish = lambda: None
    server.devices_online[1] = {"os": "?", "engine": "?"}
    before = server.aggregator.global_params
    server.is_initialized = True
    server._dispatch_round("init")           # arms the 3x leash
    deadline = time.time() + 10
    while server.round_idx < 2 and time.time() < deadline:
        time.sleep(0.05)
    assert server.round_idx >= 2, "dead rounds did not advance"
    after = server.aggregator.global_params
    np.testing.assert_array_equal(
        np.asarray(jax.tree_util.tree_leaves(before)[0]),
        np.asarray(jax.tree_util.tree_leaves(after)[0]))


class TestNativeCNN:
    """The native LeNet-class engine (train_cnn_sgd) against its flax twin
    DeviceCNN: full-batch step parity, learning on real digits, and a mixed
    native+JAX federated session."""

    def _digits(self):
        from sklearn.datasets import load_digits
        ds = load_digits()
        x = (ds.images / 16.0).astype(np.float32)[..., None]  # [n, 8, 8, 1]
        return x, ds.target.astype(np.int32)

    def _init_params(self, output_dim=10):
        import jax
        from fedml_tpu.model import create as create_model
        bundle = create_model(make_args(model="device_cnn"), output_dim)
        x0 = np.zeros((1, 8, 8, 1), np.float32)
        return bundle, jax.device_get(
            bundle.init(jax.random.PRNGKey(0), x0))

    def test_native_cnn_fullbatch_gradients_match_jax(self):
        """One full-batch step at lr=1 recovers the native gradient; it must
        equal the flax DeviceCNN gradient to float tolerance. (A small batch
        keeps post-relu zero TIES out of the max-pool windows — tie-broken
        gradient routing legitimately differs between implementations.)"""
        if not native.available():
            pytest.skip("no native toolchain")
        import jax
        import jax.numpy as jnp
        import optax
        bundle, params = self._init_params()
        x, y = self._digits()
        x, y = x[:8], y[:8]
        t = native.NativeCNNTrainer()
        nat, _ = t.train(jax.tree_util.tree_map(np.copy, params), x, y,
                         epochs=1, batch_size=len(x), lr=1.0, seed=0)

        def loss(p):
            logits = bundle.apply(p, jnp.asarray(x))
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(
                    logits, jnp.asarray(y)))

        g = jax.grad(loss)(params)
        for layer in ("Conv_0", "Conv_1", "Dense_0"):
            for leaf in ("kernel", "bias"):
                g_nat = (np.asarray(params[layer][leaf])
                         - np.asarray(nat[layer][leaf]))  # lr=1 step
                np.testing.assert_allclose(
                    g_nat, np.asarray(g[layer][leaf]),
                    rtol=1e-4, atol=1e-5, err_msg=f"{layer}/{leaf}")

    def test_native_cnn_learns_real_digits(self):
        if not native.available():
            pytest.skip("no native toolchain")
        _, params = self._init_params()
        x, y = self._digits()
        t = native.NativeCNNTrainer()
        params, loss = t.train(params, x[:1400], y[:1400], epochs=6,
                               batch_size=32, lr=0.1, seed=1)
        acc = t.evaluate(params, x[1400:], y[1400:])
        assert acc > 0.85, (acc, loss)

    def test_mixed_native_jax_cnn_federation(self, tmp_path):
        """One native-CNN device + two JAX devices train digits federated:
        the server aggregates their updates interchangeably."""
        if not native.available():
            pytest.skip("no native toolchain")
        args = make_args(model="device_cnn", dataset="digits",
                         comm_round=4, learning_rate=0.2,
                         model_file_cache_dir=str(tmp_path))
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        result = run_cross_device_inproc(args, fed, bundle,
                                         engines=["native", None, None])
        assert result is not None
        assert result["final_test_acc"] > 0.7, result["history"]


class TestNativeLSAandReader:
    def test_native_lsa_encode_decodes_with_python_pipeline(self):
        """Native Lagrange-coded sub-masks from several devices must decode
        to the exact aggregate mask with the Python server math."""
        if not native.available():
            pytest.skip("no native toolchain")
        from fedml_tpu.core.mpc.lightsecagg import decode_aggregate_mask
        P = native.PRIME
        n, privacy_t, split_t, d = 4, 1, 2, 12
        rng = np.random.RandomState(0)
        zs = [rng.randint(0, P, size=d).astype(np.uint32) for _ in range(n)]
        encs = [native.lsa_mask_encode(z, n, privacy_t, split_t, seed=50 + i)
                for i, z in enumerate(zs)]
        # every client sums the sub-masks addressed to it (all survive)
        responses = []
        for j in range(n):
            acc = np.zeros(d // split_t, np.uint64)
            for i in range(n):
                acc = (acc + encs[i][j].astype(np.uint64)) % P
            responses.append(acc)
        need = split_t + privacy_t
        z_sum = decode_aggregate_mask(responses[:need], list(range(need)),
                                      n, privacy_t, split_t, d)
        want = np.zeros(d, np.uint64)
        for z in zs:
            want = (want + z.astype(np.uint64)) % P
        np.testing.assert_array_equal(np.asarray(z_sum, np.uint64) % P, want)

    def test_native_csv_reader(self, tmp_path):
        if not native.available():
            pytest.skip("no native toolchain")
        rng = np.random.RandomState(1)
        x = rng.randn(17, 5).astype(np.float32)
        y = rng.randint(0, 3, size=17)
        path = tmp_path / "data.csv"
        with open(path, "w") as f:
            for xi, yi in zip(x, y):
                f.write(",".join(f"{v:.6f}" for v in xi) + f",{yi}\n")
        rx, ry = native.read_csv(str(path))
        np.testing.assert_allclose(rx, x, atol=1e-5)
        np.testing.assert_array_equal(ry, y)


class TestNativeArtifactAndClientManager:
    """Native serialized-model handling + the FedMLClientManager-analogue
    session (VERDICT r3 item 10): the device consumes the server's global
    model ARTIFACT and produces a server-loadable update with zero Python
    codecs, and the C-ABI session (include/fedml_client.h) trains and
    reports on-device accuracy."""

    @staticmethod
    def _digits_artifact(tmp_path):
        import jax
        from types import SimpleNamespace
        from fedml_tpu.serving import save_model
        from sklearn import datasets as skd

        ds = skd.load_digits()
        x = np.asarray(ds.data, np.float32) / 16.0
        y = np.asarray(ds.target, np.int64)
        bundle = model_mod.create(SimpleNamespace(model="lr"), 10)
        params = bundle.init(jax.random.PRNGKey(0), x[:2])
        path = str(tmp_path / "global.fmtpu")
        save_model(jax.device_get(params), path)
        return path, x, y, bundle, params

    def test_artifact_roundtrip_native_vs_python(self, tmp_path):
        if not native.available():
            pytest.skip("no native toolchain")
        from fedml_tpu.serving import load_model, save_model

        path, *_ = self._digits_artifact(tmp_path)
        # native reader sees the Python writer's bytes
        leaves = native.load_artifact_native(path)
        py = load_model(path)
        assert set(leaves) == {"Dense_0/kernel", "Dense_0/bias"}
        np.testing.assert_array_equal(leaves["Dense_0/kernel"],
                                      np.asarray(py["Dense_0"]["kernel"]))
        # native writer's bytes load with the Python reader, nested
        out = str(tmp_path / "native.fmtpu")
        native.save_artifact_native(leaves, out)
        py2 = load_model(out)
        np.testing.assert_array_equal(np.asarray(py2["Dense_0"]["bias"]),
                                      leaves["Dense_0/bias"])

    def test_client_manager_trains_and_reports_accuracy(self, tmp_path):
        if not native.available():
            pytest.skip("no native toolchain")
        from fedml_tpu.serving import load_model

        path, x, y, bundle, params = self._digits_artifact(tmp_path)
        csv = str(tmp_path / "shard.csv")
        with open(csv, "w") as f:
            for xi, yi in zip(x[:800], y[:800]):
                f.write(",".join(f"{v:.6f}" for v in xi) + f",{yi}\n")

        losses, progress = [], []
        with native.NativeClientManager() as cm:
            cm.init(path, csv, batch_size=32, learning_rate=0.3, epochs=4,
                    seed=7)
            cm.set_callbacks(on_progress=progress.append,
                             on_loss=lambda e, l: losses.append((e, l)))
            acc0 = cm.evaluate()          # global model, on-device eval
            final_loss = cm.train()
            e, l = cm.get_epoch_and_loss()
            acc1 = cm.evaluate()          # trained model, on-device eval
            out = str(tmp_path / "update.fmtpu")
            cm.save_model(out)

        assert e == 3 and abs(l - final_loss) < 1e-6
        assert len(losses) == 4 and progress[-1] == 100.0
        assert losses[0][1] > losses[-1][1]      # loss went down
        assert acc0 < 0.3 and acc1 > 0.8, (acc0, acc1)
        # the trained artifact loads server-side with the Python codec and
        # differs from the init params (a real update)
        trained = load_model(out)
        assert not np.allclose(np.asarray(trained["Dense_0"]["kernel"]),
                               np.asarray(params["Dense_0"]["kernel"]))

    def test_stop_training_interrupts(self, tmp_path):
        if not native.available():
            pytest.skip("no native toolchain")
        path, x, y, *_ = self._digits_artifact(tmp_path)
        csv = str(tmp_path / "shard.csv")
        with open(csv, "w") as f:
            for xi, yi in zip(x[:200], y[:200]):
                f.write(",".join(f"{v:.6f}" for v in xi) + f",{yi}\n")
        with native.NativeClientManager() as cm:
            cm.init(path, csv, epochs=50)
            cm.set_callbacks(
                on_loss=lambda e, l: cm.stop_training() if e == 1 else None)
            cm.train()
            e, _ = cm.get_epoch_and_loss()
        assert e <= 2  # stopped long before epoch 50
