"""Contribution assessment: LOO and GTG-Shapley must rank a helpful client
above a harmful one on a analytically transparent task."""

import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.contribution import gtg_shapley, leave_one_out


def make_problem():
    """Global param w=0; utility = -(w - 1)^2 (target w*=1). Client updates:
    two push toward 1, one pushes away."""
    params = {"w": jnp.zeros((1,))}
    updates = {"w": jnp.asarray([[1.0], [0.9], [-2.0]])}
    weights = jnp.ones((3,))

    def eval_fn(p):
        return -jnp.sum((p["w"] - 1.0) ** 2)

    return params, updates, weights, eval_fn


def test_loo_ranks_clients():
    params, updates, weights, eval_fn = make_problem()
    vals = leave_one_out(params, updates, weights, eval_fn)
    assert vals[0] > vals[2] and vals[1] > vals[2]
    assert vals[2] < 0  # harmful client has negative LOO value


def test_gtg_shapley_ranks_clients():
    params, updates, weights, eval_fn = make_problem()
    vals = gtg_shapley(params, updates, weights, eval_fn, max_perms=30,
                       truncation_eps=0.0, convergence_eps=1e-6)
    assert vals[0] > vals[2] and vals[1] > vals[2]
    # efficiency: Shapley values sum to v(N) - v(empty)
    v_full = float(eval_fn({"w": jnp.asarray([-0.1 / 3 + 1.9 / 3])}))
    # (mean update = (1+0.9-2)/3 = -0.0333 -> w = -0.0333)
    v_n = float(eval_fn({"w": jnp.zeros((1,)) + (1.0 + 0.9 - 2.0) / 3.0}))
    v_0 = float(eval_fn({"w": jnp.zeros((1,))}))
    assert abs(vals.sum() - (v_n - v_0)) < 1e-4


def test_value_fn_drivers_match_pytree_api():
    """The v(mask)-callable drivers (what the fused TPU path feeds with
    its sharded subset-evaluation kernel) must produce the SAME scores as
    the stacked-pytree API — they are the same algorithm, the callable
    just hides where the coalition value is computed."""
    from fedml_tpu.core.contribution import (gtg_shapley_values,
                                             leave_one_out_values)
    params, updates, weights, eval_fn = make_problem()

    def vfn(mask):
        w = weights * mask
        denom = jnp.maximum(jnp.sum(w), 1e-12)
        agg = jnp.sum(updates["w"] * (w / denom)[:, None], axis=0)
        return float(eval_fn({"w": params["w"] + agg}))

    loo_a = leave_one_out(params, updates, weights, eval_fn)
    loo_b = leave_one_out_values(vfn, 3)
    np.testing.assert_allclose(loo_a, loo_b, atol=1e-6)
    gtg_a = gtg_shapley(params, updates, weights, eval_fn, max_perms=10,
                        truncation_eps=0.0, convergence_eps=1e-6)
    gtg_b = gtg_shapley_values(vfn, 3, max_perms=10, truncation_eps=0.0,
                               convergence_eps=1e-6)
    np.testing.assert_allclose(gtg_a, gtg_b, atol=1e-6)


def test_manager_assess_values_records_history():
    from fedml_tpu.core.contribution import ContributionAssessorManager
    from fedml_tpu.arguments import Arguments
    mgr = ContributionAssessorManager(
        Arguments(contribution_method="loo"))
    assert mgr.enabled
    vals = mgr.assess_values(lambda mask: float(jnp.sum(mask)), 4,
                             client_ids=[7, 8, 9, 10], round_idx=2)
    # v is additive in the mask: every LOO marginal is exactly 1
    np.testing.assert_allclose(vals, np.ones(4), atol=1e-6)
    assert mgr.history[0]["round"] == 2
    assert mgr.history[0]["client_ids"] == [7, 8, 9, 10]
