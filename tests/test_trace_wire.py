"""Trace-context wire propagation: traceparent round-trips over the real
TCP and gRPC transports under chaos link faults, full in-proc cross-silo
sessions (sync + async_buffered) reconstruct as single trace trees, async
pour spans link their contributing uploads with per-link staleness, and
scripts/trace_report.py attributes >= 95% of each round's wall time.

The session tests run the REAL server/client Message FSMs over the
in-proc broker with a stub trainer (no jit, no model) so the full
handshake → broadcast → train → upload → aggregate protocol executes in
milliseconds inside tier-1."""

import json
import os
import threading
import time

import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core import mlops, obs
from fedml_tpu.core.chaos import ChaosCommManager, FaultPlan
from fedml_tpu.core.distributed.communication.message import Message
from fedml_tpu.core.obs import trace as obs_trace
from fedml_tpu.cross_silo.client.fedml_client_master_manager import (
    ClientMasterManager)
from fedml_tpu.cross_silo.server.fedml_aggregator import FedMLAggregator
from fedml_tpu.cross_silo.server.fedml_server_manager import (
    FedMLServerManager)

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _obs_defaults():
    obs.configure(None)
    yield
    obs.configure(None)
    mlops.init(Arguments(enable_tracking=False))


# --- transport-level propagation under chaos --------------------------------

def _chaos_plan():
    """Duplication + delay only (loss would eat the probe message)."""
    return FaultPlan.from_args(Arguments(
        chaos_link_dup_prob=0.5, chaos_link_delay_prob=0.5,
        chaos_link_delay_s=0.02, chaos_seed=11))


def _roundtrip_traceparent(make_mgr):
    """Send one message rank0 -> rank1 through a chaos-wrapped transport;
    return (sent span context, contexts extracted at the receiver)."""
    got, got_evt = [], threading.Event()

    class Sink:
        def receive_message(self, msg_type, msg):
            got.append(obs_trace.extract(msg))
            got_evt.set()

    m0 = ChaosCommManager(make_mgr(0), _chaos_plan(), rank=0)
    m1 = make_mgr(1)
    m1.add_observer(Sink())
    rx = threading.Thread(target=m1.handle_receive_message, daemon=True)
    rx.start()
    try:
        msg = Message("probe", 0, 1)
        msg.add_params("data", np.arange(3.0))
        with obs_trace.span("broadcast") as sp:
            obs_trace.inject(msg)
            sent = sp.context
            m0.send_message(msg)
        assert got_evt.wait(timeout=15.0), "message never arrived"
        # chaos duplication/delay may deliver extra copies — every copy
        # must carry the same context
        time.sleep(0.1)
        return sent, list(got)
    finally:
        m1.stop_receive_message()
        m0.stop_receive_message()
        rx.join(timeout=5.0)


def test_traceparent_roundtrip_tcp_under_chaos():
    from fedml_tpu.core.distributed.communication.tcp import TCPCommManager

    def make(rank):
        return TCPCommManager(rank, base_port=30110)

    sent, got = _roundtrip_traceparent(make)
    assert got and all(c is not None for c in got)
    for c in got:
        assert c.trace_id == sent.trace_id
        assert c.span_id == sent.span_id


def test_traceparent_roundtrip_grpc_under_chaos():
    grpc = pytest.importorskip("grpc")
    from fedml_tpu.core.distributed.communication.grpc import (
        GRPCCommManager)

    def make(rank):
        return GRPCCommManager(rank, base_port=30210)

    sent, got = _roundtrip_traceparent(make)
    assert got and all(c is not None for c in got)
    for c in got:
        assert c.trace_id == sent.trace_id
        assert c.span_id == sent.span_id


def test_chaos_link_fault_lands_on_sending_span():
    """A plan-scheduled fault must surface as an event on the active
    sending span — the trace-plane mirror of the fault ledger."""
    sent_plan = FaultPlan.from_args(Arguments(
        chaos_link_dup_prob=1.0, chaos_seed=3))

    class Capture:
        def __init__(self):
            self.msgs = []

        def send_message(self, msg):
            self.msgs.append(msg)

        def add_observer(self, o):
            pass

        def remove_observer(self, o):
            pass

        def notify(self, m):
            pass

        def handle_receive_message(self):
            pass

        def stop_receive_message(self):
            pass

    inner = Capture()
    mgr = ChaosCommManager(inner, sent_plan, rank=0)
    with obs_trace.span("broadcast") as sp:
        mgr.send_message(Message("t", 0, 1))
        events = [e for e in sp.events if e["name"] == "chaos.link_fault"]
    assert events, "link fault did not land on the sending span"
    assert events[0]["attrs"]["copies"] == 2
    assert len(inner.msgs) == 2  # duplicated for real


# --- full-FSM stub sessions over the in-proc broker -------------------------

class StubTrainer:
    """Millisecond 'training': nudges params and reports samples, so the
    real FSM runs end-to-end without jit."""

    def __init__(self, params, train_s=0.02):
        self.params_template = params
        self.train_s = float(train_s)

    def train(self, params, client_idx, round_idx, work_scale=1.0):
        time.sleep(self.train_s)
        new = {k: np.asarray(v) + 0.01 for k, v in params.items()}
        return new, 10.0, {"loss": 1.0}


def _run_stub_session(tmp_path, run_id, n=2, train_s=0.02, **overrides):
    from fedml_tpu.core.distributed.communication.inproc import InProcBroker

    base = dict(client_num_in_total=n, client_num_per_round=n,
                comm_round=2, training_type="cross_silo",
                random_seed=5, log_file_dir=str(tmp_path), run_id=run_id)
    base.update(overrides)
    args = Arguments(**base)
    args.inproc_broker = InProcBroker()
    mlops.init(args)
    global_params = {"w": np.zeros(4, np.float32)}
    if str(getattr(args, "round_mode", "sync")) == "async_buffered":
        from fedml_tpu.cross_silo.server.async_server import (
            AsyncFedMLAggregator, AsyncFedMLServerManager)
        agg = AsyncFedMLAggregator(args, global_params)
        server = AsyncFedMLServerManager(args, agg, rank=0, size=n + 1,
                                         backend="INPROC")
    else:
        agg = FedMLAggregator(args, global_params)
        server = FedMLServerManager(args, agg, rank=0, size=n + 1,
                                    backend="INPROC")
    clients = [ClientMasterManager(args, StubTrainer(global_params,
                                                     train_s=train_s),
                                   rank=r, size=n + 1, backend="INPROC")
               for r in range(1, n + 1)]
    threads = [threading.Thread(target=c.run, daemon=True)
               for c in clients]
    for t in threads:
        t.start()
    done = {}

    def run_server():
        server.run()
        done["ok"] = True

    st = threading.Thread(target=run_server, daemon=True)
    st.start()
    st.join(timeout=60.0)
    assert done.get("ok"), "stub session stalled"
    assert server.result is not None
    for t in threads:
        t.join(timeout=5.0)
    mlops.init(Arguments(enable_tracking=False))  # detach sink
    return os.path.join(str(tmp_path), f"run_{run_id}.jsonl"), server


def _spans(path):
    return [json.loads(l) for l in open(path)
            if l.strip() and json.loads(l)["kind"] == "span"]


def test_sync_session_reconstructs_single_trace_tree(tmp_path):
    """One round = one trace: the broadcast's context crosses the wire,
    every silo's train/upload spans join the SAME trace, and the tree is
    fully connected from the round root."""
    path, _ = _run_stub_session(tmp_path, "sync_tree")
    spans = _spans(path)
    rounds = [s for s in spans if s["name"] == "round"]
    assert len(rounds) == 2  # comm_round=2
    for root in rounds:
        tree = [s for s in spans if s["trace_id"] == root["trace_id"]]
        by_id = {s["span_id"]: s for s in tree}
        # single root; every other span reaches it via parent links
        roots = [s for s in tree if s["parent_id"] is None]
        assert roots == [root]
        for s in tree:
            seen = set()
            cur = s
            while cur["parent_id"] is not None:
                assert cur["span_id"] not in seen
                seen.add(cur["span_id"])
                cur = by_id[cur["parent_id"]]  # KeyError = broken tree
            assert cur is root
        names = {s["name"] for s in tree}
        assert {"broadcast", "wait.uploads", "aggregate",
                "silo.round", "train", "upload"} <= names, names
        # per-silo subtrees hang off the broadcast (context via the wire)
        bcast = next(s for s in tree if s["name"] == "broadcast")
        silo = [s for s in tree if s["name"] == "silo.round"]
        assert len(silo) == 2
        assert all(s["parent_id"] == bcast["span_id"] for s in silo)
        # the wait span linked each silo's upload span
        wait = next(s for s in tree if s["name"] == "wait.uploads")
        upload_ids = {s["span_id"] for s in tree if s["name"] == "upload"}
        linked = {l["span_id"] for l in wait.get("links", [])}
        assert linked == upload_ids


def test_sync_session_trace_report_attributes_95pct(tmp_path):
    # train_s sets the round's wall time: the few ms of span bookkeeping
    # between adjacent spans are constant, so a realistically-sized round
    # (0.25 s vs real silos' minutes) is what the 95% bar is about
    path, _ = _run_stub_session(tmp_path, "sync_attr", train_s=0.25)
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts"))
    import io

    import trace_report
    out = io.StringIO()
    rc = trace_report.print_report(trace_report.load_spans([path]),
                                   None, min_attr=0.95, out=out)
    assert rc == 0, out.getvalue()


def test_async_session_pour_links_uploads_with_staleness(tmp_path):
    """Async acceptance: pour spans LINK their contributing upload spans
    (the fan-in a parent tree cannot express), staleness attached per
    link, and every linked span exists in the log with a silo.round
    parent chain back to the async.sync that dispatched it."""
    path, server = _run_stub_session(
        tmp_path, "async_tree", comm_round=3,
        round_mode="async_buffered", async_buffer_k=2,
        async_pour_timeout_s=10.0)
    assert server.aggregator.version >= 3
    spans = _spans(path)
    by_id = {s["span_id"]: s for s in spans}
    pours = [s for s in spans if s["name"] == "pour"
             and (s.get("attrs", {}) or {}).get("poured")]
    assert len(pours) >= 3
    upload_spans = {s["span_id"]: s for s in spans
                    if s["name"] == "upload"}
    for pour in pours:
        links = pour.get("links", [])
        assert len(links) == pour["attrs"]["poured"]
        for ln in links:
            at = ln.get("attrs", {})
            assert "staleness" in at and at["staleness"] >= 0
            assert "dispatch_version" in at
            # the linked span IS a real upload span from another trace
            target = upload_spans[ln["span_id"]]
            assert target["trace_id"] == ln["trace_id"]
            assert target["trace_id"] != pour["trace_id"]
            # ...whose parent chain reaches the dispatching async.sync
            silo = by_id[target["parent_id"]]
            assert silo["name"] == "silo.round"
            sync = by_id[silo["parent_id"]]
            assert sync["name"] == "async.sync"
            assert sync["attrs"]["version"] == at["dispatch_version"]


def test_stub_session_jsonl_validates(tmp_path):
    """Cross-silo (not just engine) logs hold to the schema table —
    including the async pour's chaos/arrival records with trace ids."""
    from fedml_tpu.core.obs import schema as obs_schema
    path, _ = _run_stub_session(
        tmp_path, "async_schema", comm_round=2,
        round_mode="async_buffered", async_buffer_k=2,
        async_pour_timeout_s=10.0)
    problems = obs_schema.validate_lines(open(path).read().splitlines())
    assert not problems, problems[:20]
