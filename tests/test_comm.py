"""Communication layer: Message wire format, transports (inproc/TCP/gRPC),
CommManager FSM dispatch, topologies, and the Flow DAG."""

import threading
import time

import numpy as np
import pytest

from fedml_tpu.core.distributed.communication.message import (
    Message, tree_to_wire, wire_to_tree)
from fedml_tpu.core.distributed.communication.inproc import (InProcBroker,
                                                             InProcCommManager)
from fedml_tpu.core.distributed.fedml_comm_manager import FedMLCommManager


class TestMessage:
    def test_roundtrip_scalars(self):
        m = Message("test_type", 1, 2)
        m.add_params("alpha", 0.5)
        m.add_params("name", "abc")
        m2 = Message.decode(m.encode())
        assert m2.get_type() == "test_type"
        assert m2.get_sender_id() == 1 and m2.get_receiver_id() == 2
        assert m2.get("alpha") == 0.5 and m2.get("name") == "abc"

    def test_roundtrip_arrays(self):
        m = Message(3, 0, 1)
        arr = np.random.RandomState(0).randn(4, 5).astype(np.float32)
        m.add_params("model", {"w": arr, "b": np.arange(7)})
        m2 = Message.decode(m.encode())
        np.testing.assert_array_equal(m2.get("model")["w"], arr)
        np.testing.assert_array_equal(m2.get("model")["b"], np.arange(7))

    def test_tree_wire_roundtrip(self):
        import jax.numpy as jnp
        tree = {"layer": {"kernel": jnp.ones((3, 2)), "bias": jnp.zeros(2)},
                "head": [jnp.arange(4.0)]}
        wire = tree_to_wire(tree)
        back = wire_to_tree(wire, tree)
        np.testing.assert_array_equal(np.asarray(back["layer"]["kernel"]),
                                      np.ones((3, 2)))
        np.testing.assert_array_equal(np.asarray(back["head"][0]),
                                      np.arange(4.0))


def _echo_pair(make_comm):
    """rank 1 echoes rank 0's payload back; returns what rank 0 received."""
    got = {}

    class Echo(FedMLCommManager):
        def register_message_receive_handlers(self):
            self.register_message_receive_handler("ping", self.on_ping)
            self.register_message_receive_handler("pong", self.on_pong)

        def on_ping(self, msg):
            out = Message("pong", self.rank, msg.get_sender_id())
            out.add_params("data", msg.get("data"))
            self.send_message(out)

        def on_pong(self, msg):
            got["data"] = msg.get("data")
            self.finish()

    m0 = Echo(*make_comm(0))
    m1 = Echo(*make_comm(1))
    t1 = threading.Thread(target=m1.run, daemon=True)
    t1.start()
    msg = Message("ping", 0, 1)
    msg.add_params("data", np.arange(10.0))
    m0.send_message(msg)
    t0 = threading.Thread(target=m0.run, daemon=True)
    t0.start()
    t0.join(timeout=15.0)
    m1.finish()
    t1.join(timeout=5.0)
    return got.get("data")


class _Args:
    pass


class TestTransports:
    def test_inproc(self):
        broker = InProcBroker()
        args = _Args()
        args.inproc_broker = broker

        def make(rank):
            return (args, None, rank, 2, "INPROC")

        data = _echo_pair(make)
        np.testing.assert_array_equal(data, np.arange(10.0))

    def test_tcp(self):
        args = _Args()
        args.tcp_base_port = 29870

        def make(rank):
            return (args, None, rank, 2, "TCP")

        data = _echo_pair(make)
        np.testing.assert_array_equal(data, np.arange(10.0))

    def test_grpc(self):
        args = _Args()
        args.grpc_base_port = 29970

        def make(rank):
            return (args, None, rank, 2, "GRPC")

        data = _echo_pair(make)
        np.testing.assert_array_equal(data, np.arange(10.0))


class TestTopology:
    def test_symmetric_ring(self):
        from fedml_tpu.core.distributed.topology import SymmetricTopologyManager
        tm = SymmetricTopologyManager(6, neighbor_num=2)
        tm.generate_topology()
        w = tm.mixing_matrix()
        np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-9)
        np.testing.assert_allclose(w, w.T * (w.T.sum(1) / w.sum(1))[:, None],
                                   atol=1e-9)  # symmetric sparsity
        assert tm.get_out_neighbor_idx_list(0) == [1, 5]

    def test_asymmetric(self):
        from fedml_tpu.core.distributed.topology import (
            AsymmetricTopologyManager)
        tm = AsymmetricTopologyManager(5, neighbor_num=2, seed=1)
        tm.generate_topology()
        w = tm.mixing_matrix()
        np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-9)
        assert all(1 <= len(tm.get_out_neighbor_idx_list(i)) for i in range(5))


class TestFlow:
    def test_flow_chain_with_loop(self):
        from fedml_tpu.core.distributed.flow import (FedMLAlgorithmFlow,
                                                     FedMLExecutor)

        class Server(FedMLExecutor):
            def init_model(self):
                self.set_params(0)
                return 0

            def aggregate(self, v=None):
                self.set_params(self.get_params() + (v or 0))
                return self.get_params()

        class Client(FedMLExecutor):
            def train(self, v=None):
                return (v or 0) + 1

        class A:
            comm_round = 3

        server, client = Server(0), Client(1)
        flow = FedMLAlgorithmFlow(A(), server)
        flow.add_flow("init", server.init_model)
        flow.add_flow("train", client.train, loop=True)
        flow.add_flow("agg", server.aggregate, loop=True)
        flow.add_flow("done", server.aggregate)
        flow.build()
        out = flow.run()
        # 3 loop iterations: agg accumulates 1 three times -> 3; final agg
        # adds the last value again
        assert server.get_params() >= 3
