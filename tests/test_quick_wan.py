"""Quick-gate WAN + optimizer coverage: the full cross-silo FSM and
optimizer SP<->TPU parity suites are slow-tier, but the quick gate must
exercise at least one real session and one parity case so a regression in
either pillar cannot slip through a fast CI pass (VERDICT r2 #10)."""

import jax
import numpy as np

import fedml_tpu
from fedml_tpu import data as data_mod
from fedml_tpu import model as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_silo.horizontal.runner import run_cross_silo_inproc


def test_minimal_cross_silo_session():
    """2 silos x 2 rounds over the in-proc broker: the client/server FSMs,
    wire codec, and weighted aggregation all fire."""
    args = Arguments(dataset="synthetic_mnist", model="lr",
                     client_num_in_total=2, client_num_per_round=2,
                     comm_round=2, epochs=1, batch_size=32,
                     learning_rate=0.1, random_seed=5,
                     training_type="cross_silo")
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    result = run_cross_silo_inproc(args, fed, bundle)
    assert result is not None
    assert result["rounds"] == 2
    assert result["final_test_acc"] > 0.3  # 2 rounds of lr on easy data


def test_scaffold_sp_tpu_parity_quick():
    """One stateful-optimizer parity case (SCAFFOLD carries control
    variates through client state — the hardest state plumbing)."""
    kw = dict(dataset="synthetic_mnist", model="lr",
              client_num_in_total=4, client_num_per_round=3,
              comm_round=2, epochs=1, batch_size=32, learning_rate=0.1,
              random_seed=11, federated_optimizer="scaffold")
    r_sp = fedml_tpu.run_simulation(backend="sp", args=Arguments(**kw))
    r_tpu = fedml_tpu.run_simulation(backend="tpu", args=Arguments(**kw))
    for a, b in zip(jax.tree_util.tree_leaves(r_sp["params"]),
                    jax.tree_util.tree_leaves(r_tpu["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
