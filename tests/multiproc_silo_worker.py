"""Worker for test_multiprocess_mesh: one HOST of a two-process silo.

Each OS process owns 4 virtual CPU devices; ``init_silo_process_group``
(the torchrun-env contract) joins them into ONE 8-device JAX runtime, and
the hierarchical silo trainer then runs its data-parallel local step over
the GLOBAL mesh — the same program a real multi-host TPU silo runs. Rank 0
writes the round result to ``sys.argv[1]`` for the pytest process to
compare against the single-process golden.
"""

import json
import os
import sys


def main() -> None:
    out_path = sys.argv[1]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from fedml_tpu.cross_silo.hierarchical.process_group import (
        init_silo_process_group)
    assert init_silo_process_group(), "WORLD_SIZE env contract not seen"
    assert len(jax.local_devices()) == 4
    assert len(jax.devices()) == 8, f"global mesh is {len(jax.devices())}"

    import numpy as np
    from fedml_tpu import data as data_mod, model as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import make_trainer_spec
    from fedml_tpu.cross_silo.hierarchical.trainer import (
        HierarchicalSiloTrainer)
    from fedml_tpu.optimizers.registry import create_optimizer

    args = Arguments(dataset="digits", model="lr", client_num_in_total=2,
                     client_num_per_round=2, comm_round=1, epochs=1,
                     batch_size=32, learning_rate=0.1, random_seed=7,
                     training_type="cross_silo")
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    spec = make_trainer_spec(fed, bundle)
    opt = create_optimizer(args, spec)
    # the silo's mesh = the GLOBAL device list spanning both processes
    trainer = HierarchicalSiloTrainer(args, fed, bundle, spec, opt,
                                      devices=jax.devices())
    params = trainer.params_template

    # one FedAvg round across 2 clients, both trained by this silo program
    deltas, ws = [], []
    for cid in range(2):
        new_p, n, _ = trainer.train(params, cid, 0)
        deltas.append(jax.tree_util.tree_map(
            lambda a, b: np.asarray(a) - np.asarray(b), new_p, params))
        ws.append(n)
    wsum = sum(ws)
    agg = jax.tree_util.tree_map(
        lambda *ds: sum(w * d for w, d in zip(ws, ds)) / wsum, *deltas)
    new_params = jax.tree_util.tree_map(
        lambda p, u: np.asarray(p) + u, params, agg)

    if jax.process_index() == 0:
        flat = np.concatenate([np.asarray(l).ravel() for l in
                               jax.tree_util.tree_leaves(new_params)])
        with open(out_path, "w") as f:
            json.dump({"n_global_devices": len(jax.devices()),
                       "n_processes": jax.process_count(),
                       "weights": ws,
                       "params_sum": float(flat.sum()),
                       "params": flat[:4096].tolist()}, f)
    # all processes must reach teardown together
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
