"""Fleet-fast serving (ISSUE 17): generated-token suffix caching
(scheduler chain insert + chat-surface re-encode round trip), cache-aware
gateway routing with KV-headroom spill, quarantine heal-by-probe,
SLO-driven autoscaling, drain-before-kill scale-down under live SSE
streams, and the deterministic mixed-tenant load generator — plus the
knob-off defaults that keep the PR 16 wire byte-identical.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.llm.data import BOS, SEP, ByteTokenizer, RoundTripByteTokenizer
from fedml_tpu.llm.federated import build_llm
from fedml_tpu.serving.autoscale import (Autoscaler, FleetSLOView, Gateway,
                                         ReplicaSet, SLOPolicy)
from fedml_tpu.serving.batch import DecodeScheduler
from fedml_tpu.serving.llm_template import (CausalLMPredictor,
                                            ChatCompletionRunner)

pytestmark = pytest.mark.serving

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))


def _args(**kw):
    base = dict(dataset="llm_synthetic", model="causal_lm",
                client_num_in_total=2, client_num_per_round=2,
                comm_round=1, epochs=1, batch_size=4, learning_rate=1e-3,
                random_seed=3, llm_hidden_size=32, llm_num_layers=2,
                llm_num_heads=2, llm_intermediate_size=64,
                llm_max_seq_len=128, lora_rank=4)
    base.update(kw)
    return Arguments(**base)


@pytest.fixture(scope="module")
def setup():
    import jax
    args = _args()
    _, bundle, _, tok = build_llm(args)
    params = bundle.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))
    return args, bundle, params, tok


def _sched(bundle, **kw):
    opts = dict(slots=4, block_size=8, prefill_chunk=8)
    opts.update(kw)
    return DecodeScheduler(bundle.module, bundle.cfg, bundle.base_params,
                           None, **opts)


def _run(sched, ids, n=6, seed=0, temp=0.0, final=True):
    slot, first = sched.admit(ids, seed=seed, temperature=temp,
                              max_new_tokens=n)
    out = [first]
    for _ in range(n - 1):
        out.append(sched.step()[slot])
    if final:
        sched.release(slot, final_ids=list(ids) + out)
    else:
        sched.release(slot)
    return out


# ------------------------------------------------------------ tokenizer ----

class TestRoundTripTokenizer:
    def test_exact_inverse_over_every_byte_token(self):
        tok = RoundTripByteTokenizer()
        ids = list(range(4, 260))          # every byte token
        assert tok.encode(tok.decode(ids)) == ids
        # invalid UTF-8 runs — the sequences an untrained model emits
        bad = [244, 199, 132, 250, 250]
        assert tok.encode(tok.decode(bad)) == bad

    def test_matches_byte_tokenizer_on_valid_utf8(self):
        lossy, exact = ByteTokenizer(), RoundTripByteTokenizer()
        for text in ("hello fleet", "héllo — ünïcode", "日本語"):
            assert exact.encode(text) == lossy.encode(text)
            assert exact.decode(exact.encode(text)) == text

    def test_lone_surrogates_survive_the_json_wire(self):
        tok = RoundTripByteTokenizer()
        ids = [119, 244, 199, 132, 120]
        text = tok.decode(ids)
        back = json.loads(json.dumps({"content": text}).encode())["content"]
        assert back == text and tok.encode(back) == ids


# -------------------------------------------- scheduler-level suffix cache ----

class TestSuffixScheduler:
    def test_followup_aliases_generated_blocks(self, setup):
        _, bundle, _, tok = setup
        sched = _sched(bundle, prefix_cache=True, suffix_cache=True)
        ids = [BOS] + tok.encode("suffix caching turn one, long enough "
                                 "to span KV blocks") + [SEP]
        out = _run(sched, ids, n=16, seed=5)
        idx = sched._index
        assert idx.debug_state().get("decode_blocks", 0) >= 1
        # follow-up: prior prompt ++ generated reply ++ new user turn
        ids2 = ids + out + tok.encode("\nand turn two") + [SEP]
        before = idx.suffix_tokens_reused
        slot, _ = sched.admit(ids2, seed=6, temperature=0.0,
                              max_new_tokens=4)
        assert idx.suffix_hits >= 1
        assert idx.suffix_tokens_reused > before
        sched.release(slot)

    def test_suffix_reuse_is_bit_identical(self, setup):
        _, bundle, _, tok = setup
        warm = _sched(bundle, prefix_cache=True, suffix_cache=True)
        ids = [BOS] + tok.encode("bit identity over aliased decode "
                                 "blocks must hold exactly") + [SEP]
        out = _run(warm, ids, n=16, seed=9)
        ids2 = ids + out + tok.encode("\nsecond turn") + [SEP]
        reused = _run(warm, ids2, n=8, seed=11)
        assert warm._index.suffix_hits >= 1
        cold = _sched(bundle, prefix_cache=False, suffix_cache=False)
        ref = _run(cold, ids2, n=8, seed=11, final=False)
        assert reused == ref

    def test_short_chain_never_indexes_partial_blocks(self, setup):
        _, bundle, _, tok = setup
        sched = _sched(bundle, prefix_cache=True, suffix_cache=True)
        ids = [BOS] + tok.encode("ti") + [SEP]
        _run(sched, ids, n=3, seed=1)   # 4 + 3 < block_size: no full block
        assert sched._index.debug_state().get("decode_blocks", 0) == 0

    def test_knob_off_keeps_legacy_release_and_no_decode_blocks(self, setup):
        _, bundle, _, tok = setup
        sched = _sched(bundle, prefix_cache=True)
        assert sched.suffix_cache is False
        ids = [BOS] + tok.encode("knob off path stays put") + [SEP]
        out = _run(sched, ids, n=16, seed=2, final=False)
        assert len(out) == 16
        assert sched._index.debug_state().get("decode_blocks", 0) == 0
        assert sched._index.suffix_hits == 0


# ------------------------------------------------ chat-surface suffix cache ----

@pytest.fixture(scope="module")
def suffix_pred(setup):
    _, bundle, params, tok = setup
    pred = CausalLMPredictor(bundle, params, tokenizer=tok, mode="batch",
                             stream=True,
                             batch_opts={"slots": 4, "block_size": 8,
                                         "prefill_chunk": 8,
                                         "prefix_cache": True,
                                         "suffix_cache": True})
    yield pred
    pred.close()


def _chat(pred, messages, max_tokens=16, seed=7, stream=False):
    req = {"messages": messages, "max_tokens": max_tokens,
           "temperature": 0.0, "seed": seed}
    if stream:
        req["stream"] = True
        acc, usage = "", None
        for ev in pred.chat(req).events:
            ch = ev["choices"][0]
            acc += ch["delta"].get("content", "")
            if ch.get("finish_reason"):
                usage = ch.get("usage")
        return acc, usage
    out = pred.chat(req)
    return (out["choices"][0]["message"]["content"], out["usage"])


class TestSuffixChatSurface:
    MSGS = [{"role": "system", "content": "you are the fleet test bot"},
            {"role": "user", "content": "say something"}]

    def test_multi_turn_followup_hits_suffix_cache(self, suffix_pred):
        idx = suffix_pred.engine.scheduler._index
        reply, usage = _chat(suffix_pred, self.MSGS, seed=7)
        assert usage["completion_tokens"] > 0
        h0, t0 = idx.suffix_hits, idx.suffix_tokens_reused
        msgs2 = self.MSGS + [
            {"role": "assistant", "content": reply},
            {"role": "user", "content": "and again please"}]
        _chat(suffix_pred, msgs2, seed=8)
        assert idx.suffix_hits > h0
        assert idx.suffix_tokens_reused > t0

    def test_stream_deltas_reencode_to_generated_ids(self, suffix_pred):
        idx = suffix_pred.engine.scheduler._index
        reply, _ = _chat(suffix_pred, self.MSGS, seed=7)
        acc, usage = _chat(suffix_pred, self.MSGS, seed=7, stream=True)
        # per-token lossless deltas concatenate to the non-stream reply
        assert acc == reply and usage["completion_tokens"] > 0
        h0 = idx.suffix_hits
        msgs3 = self.MSGS + [
            {"role": "assistant", "content": acc},
            {"role": "user", "content": "third turn now"}]
        _chat(suffix_pred, msgs3, max_tokens=8, seed=9)
        assert idx.suffix_hits > h0

    def test_warm_repeat_is_bit_identical(self, suffix_pred):
        r1, u1 = _chat(suffix_pred, self.MSGS, seed=7)
        r2, u2 = _chat(suffix_pred, self.MSGS, seed=7)
        assert r1 == r2
        assert u1["completion_tokens"] == u2["completion_tokens"]

    def test_no_recompile_across_suffix_reuse(self, suffix_pred,
                                              xla_compile_counter):
        reply, _ = _chat(suffix_pred, self.MSGS, seed=7)   # warm programs
        msgs2 = self.MSGS + [
            {"role": "assistant", "content": reply},
            {"role": "user", "content": "steady state turn"}]
        _chat(suffix_pred, msgs2, seed=8)
        xla_compile_counter.reset()
        reply_b, _ = _chat(suffix_pred, [
            {"role": "system", "content": "you are the other tenant bot"},
            {"role": "user", "content": "different text same shapes"}],
            seed=12)
        _chat(suffix_pred, [
            {"role": "system", "content": "you are the other tenant bot"},
            {"role": "user", "content": "different text same shapes"},
            {"role": "assistant", "content": reply_b},
            {"role": "user", "content": "follow up"}], seed=13)
        assert xla_compile_counter.delta() == 0

    def test_tokenizer_swapped_only_when_knob_on(self, setup, suffix_pred):
        _, bundle, params, tok = setup
        assert isinstance(suffix_pred.tokenizer, RoundTripByteTokenizer)
        off = CausalLMPredictor(bundle, params, tokenizer=tok, mode="batch",
                                stream=True,
                                batch_opts={"slots": 2, "block_size": 8,
                                            "prefill_chunk": 8})
        try:
            assert off.tokenizer is tok        # knob off: untouched
            assert off._suffix_chat is False
            assert off.engine.scheduler.suffix_cache is False
        finally:
            off.close()


# ------------------------------------------------------ cache-aware routing ----

class _FakePorts:
    def __init__(self, ports):
        self._p = list(ports)

    def ports(self, include_draining=False):
        return list(self._p)


def _routed_gateway(ports, monkeypatch, headroom=8, **kw):
    gw = Gateway(_FakePorts(ports), cache_aware=True, **kw)
    hr = dict((p, headroom) for p in ports)
    monkeypatch.setattr(Gateway, "_replica_headroom",
                        lambda self, port: hr.get(port), raising=True)
    return gw, hr


class TestCacheAwareRouting:
    def test_same_digest_sticks_to_its_warm_replica(self, monkeypatch):
        gw, _ = _routed_gateway([7001, 7002, 7003], monkeypatch)
        d = gw._routing_digest({"messages": [
            {"role": "system", "content": "tenant zero system prompt"}]})
        assert d is not None
        home = gw._pick_port(set(), False, digest=d)
        for _ in range(6):   # round-robin pointer moves; the digest wins
            assert gw._pick_port(set(), False, digest=d) == home
        # a different digest may land elsewhere without evicting the home
        other = gw._routing_digest({"prompt": "completely different lead"})
        gw._pick_port(set(), False, digest=other)
        assert gw._warm[d] == home

    def test_digest_keys_on_leading_bytes_only(self):
        gw = Gateway(_FakePorts([7001]), cache_aware=True, digest_chars=32)
        head = "x" * 40
        d1 = gw._routing_digest({"prompt": head + "tail one"})
        d2 = gw._routing_digest({"prompt": head + "another tail"})
        assert d1 == d2
        assert gw._routing_digest({"prompt": "y" + head}) != d1

    def test_saturated_warm_replica_spills_without_rehoming(self,
                                                            monkeypatch):
        gw, hr = _routed_gateway([7001, 7002], monkeypatch,
                                 spill_headroom=2)
        d = gw._routing_digest({"prompt": "sticky tenant prompt"})
        home = gw._pick_port(set(), False, digest=d)
        hr[home] = 0                       # saturate the home replica
        picks = {gw._pick_port(set(), False, digest=d) for _ in range(4)}
        assert home not in picks or len(picks) > 1   # traffic spilled
        assert gw._warm[d] == home         # cache home NOT rehomed
        hr[home] = 8
        assert gw._pick_port(set(), False, digest=d) == home

    def test_departed_home_rehomes_to_a_live_replica(self, monkeypatch):
        gw, _ = _routed_gateway([7001, 7002], monkeypatch)
        d = gw._routing_digest({"prompt": "rehome on scale-down"})
        home = gw._pick_port(set(), False, digest=d)
        gw.replica_set._p.remove(home)
        fresh = gw._pick_port(set(), False, digest=d)
        assert fresh != home and fresh in gw.replica_set.ports()
        assert gw._warm[d] == fresh

    def test_unknown_headroom_never_blocks_the_warm_pick(self, monkeypatch):
        gw, hr = _routed_gateway([7001, 7002], monkeypatch)
        d = gw._routing_digest({"prompt": "scrape-less replica"})
        home = gw._pick_port(set(), False, digest=d)
        hr[home] = None                    # no slo payload / no answer
        assert gw._pick_port(set(), False, digest=d) == home

    def test_cache_off_is_plain_round_robin(self):
        gw = Gateway(_FakePorts([7001, 7002]))
        assert gw.cache_aware is False
        picks = [gw._pick_port(set(), False, digest=None)
                 for _ in range(4)]
        assert picks == [7001, 7002, 7001, 7002]
        assert not gw._warm

    def test_warm_map_is_lru_bounded(self, monkeypatch):
        gw, _ = _routed_gateway([7001], monkeypatch)
        gw._warm_cap = 8
        for i in range(40):
            gw._pick_port(set(), False,
                          digest=gw._routing_digest({"prompt": f"t{i}"}))
        assert len(gw._warm) <= 8


# -------------------------------------------------------- quarantine heal ----

class _FlappingPredictor:
    """Stub whose /healthz flips between ok and sick on demand — the
    flapping replica the heal probe must keep OUT of rotation."""

    def __init__(self, state):
        self._state = state

    def predict(self, request):
        return {"pong": 1}

    def ready(self):
        return True

    def health(self):
        return {"status": "ok" if self._state["ok"] else "degraded",
                "queue_depth": 0}


class TestQuarantineHeal:
    def test_heal_probe_gates_rejoin_and_rearms_on_failure(self):
        from fedml_tpu.serving import FedMLInferenceRunner
        state = {"ok": False}
        runner = FedMLInferenceRunner(_FlappingPredictor(state))
        port = runner.start()
        try:
            gw = Gateway(_FakePorts([port]), unhealthy_ttl_s=0.05,
                         heal_probe=True)
            gw._mark_unhealthy(port, "test")
            time.sleep(0.1)   # TTL expired
            # probe-gated: expiry alone does NOT rejoin a sick replica
            assert gw._is_quarantined(port)
            assert gw.heal() == 0          # failing probe re-arms
            assert gw._is_quarantined(port)
            state["ok"] = True
            time.sleep(0.1)   # wait out the re-armed TTL
            assert gw.heal() == 1
            assert not gw._is_quarantined(port)
        finally:
            runner.stop()

    def test_legacy_ttl_rejoin_with_probe_off(self):
        gw = Gateway(_FakePorts([7009]), unhealthy_ttl_s=0.05)
        gw._mark_unhealthy(7009, "test")
        assert gw._is_quarantined(7009)
        time.sleep(0.1)
        assert not gw._is_quarantined(7009)   # timer-only rejoin
        assert gw.heal() == 0                 # no-op with probe off


# ------------------------------------------------------------ SLO policy ----

class TestSLOPolicy:
    def _fleet(self, **kw):
        base = dict(ttft_p99_s=0.0, itl_p99_s=0.0, queue_depth=0,
                    kv_headroom_min=None, gateway_p99_s=0.0, replicas=2)
        base.update(kw)
        return FleetSLOView(**base)

    def test_each_breach_signal_scales_up(self):
        p = SLOPolicy(ttft_p99_s=0.5, itl_p99_s=0.1,
                      queue_depth_per_replica=4.0, kv_headroom_min=1,
                      cooldown_s=0.0)
        assert p.breaches(self._fleet(ttft_p99_s=0.9), 2) == ["ttft_p99"]
        assert p.breaches(self._fleet(itl_p99_s=0.2), 2) == ["itl_p99"]
        assert p.breaches(self._fleet(queue_depth=9), 2) == ["queue_depth"]
        assert p.breaches(self._fleet(kv_headroom_min=0), 2) \
            == ["kv_headroom"]
        assert p.desired_from_fleet(self._fleet(queue_depth=9), 2) == 3

    def test_disabled_targets_never_breach(self):
        p = SLOPolicy(ttft_p99_s=0.0, itl_p99_s=0.0,
                      queue_depth_per_replica=0.0, kv_headroom_min=0,
                      cooldown_s=0.0)
        assert p.breaches(self._fleet(ttft_p99_s=99, itl_p99_s=99,
                                      queue_depth=999,
                                      kv_headroom_min=0), 2) == []

    def test_cooldown_gates_consecutive_moves(self):
        p = SLOPolicy(queue_depth_per_replica=4.0, cooldown_s=60.0)
        assert p.desired_from_fleet(self._fleet(queue_depth=99), 2) == 3
        # inside the cooldown the same breach holds the fleet
        assert p.desired_from_fleet(self._fleet(queue_depth=99), 3) == 3

    def test_idle_fleet_scales_down_one_step(self):
        p = SLOPolicy(ttft_p99_s=1.0, queue_depth_per_replica=4.0,
                      cooldown_s=0.0)
        idle = self._fleet(ttft_p99_s=0.01, queue_depth=0)
        assert p.desired_from_fleet(idle, 3) == 2
        assert p.desired_from_fleet(idle, 1) == 1   # never below one
        # near-target tails are NOT idle: hold
        warm = self._fleet(ttft_p99_s=0.8, queue_depth=0)
        assert p.desired_from_fleet(warm, 3) == 3

    def test_legacy_signature_feeds_the_gateway_tail(self):
        p = SLOPolicy(ttft_p99_s=0.5, cooldown_s=0.0)
        assert p.desired_replicas(10.0, 0.9, 2) == 3
        assert p.desired_replicas(10.0, 0.01, 2) == 1


class TestAutoscalerFleetLoop:
    def test_slo_step_scales_on_queue_and_headroom(self):
        state = {"ok": True}

        class _Busy(_FlappingPredictor):
            def health(self):
                return {"status": "ok", "queue_depth": state["queue"],
                        "slo": {"ttft_p99_s": 0.0, "ttft_n": 0,
                                "itl_p99_s": 0.0, "itl_n": 0,
                                "kv_headroom_requests": state["headroom"]}}

        state.update(queue=0, headroom=8)
        rs = ReplicaSet(lambda: _Busy(state), min_replicas=1,
                        max_replicas=3)
        gw = Gateway(rs)
        asc = Autoscaler(gw, SLOPolicy(queue_depth_per_replica=4.0,
                                       kv_headroom_min=1, cooldown_s=0.0))
        try:
            state["queue"] = 9            # queue breach -> +1
            assert asc.step() == 2
            # the scrape feeding the move saw the pre-scale single replica
            assert asc.last_fleet.queue_depth == 9
            state["queue"] = 0
            state["headroom"] = 0         # saturation breach -> +1
            assert asc.step() == 3
            assert asc.last_fleet.kv_headroom_min == 0
            state["headroom"] = 8         # idle fleet drains back
            assert asc.step() == 2
            assert asc.scale_events == 3
        finally:
            rs.stop()


# ------------------------------------- drain-before-kill under live streams ----

class TestFleetDrainZeroDrops:
    def _stream(self, gw, results, i):
        acc, finish, usage = "", None, None
        try:
            for ev in gw.stream({"messages": [
                    {"role": "system", "content": "drain test bot"},
                    {"role": "user", "content": f"stream {i} please"}],
                    "stream": True, "max_tokens": 6, "temperature": 0.0,
                    "seed": 40 + i}, timeout=120.0):
                ch = json.loads(ev)["choices"][0]
                acc += (ch.get("delta") or {}).get("content", "")
                if ch.get("finish_reason"):
                    finish = ch["finish_reason"]
                    usage = ch.get("usage")
            results[i] = (finish, usage, acc, None)
        except Exception as e:  # noqa: BLE001 — recorded, asserted below
            results[i] = (None, None, acc, e)

    def test_restart_and_scale_down_drop_zero_tokens(self, setup):
        _, bundle, params, tok = setup

        def factory():
            return CausalLMPredictor(
                bundle, params, tokenizer=tok, mode="batch", stream=True,
                batch_opts={"slots": 4, "block_size": 8,
                            "prefill_chunk": 8, "prefix_cache": True,
                            "suffix_cache": True})

        rs = ReplicaSet(predictor_factory=factory, min_replicas=1,
                        max_replicas=2, runner_cls=ChatCompletionRunner,
                        drain_grace_s=30.0)
        try:
            rs.scale_to(2)
            gw = Gateway(rs)
            for i in range(2):   # warm both replicas' programs
                gw.predict({"messages": [
                    {"role": "user", "content": "warm up"}],
                    "max_tokens": 2, "temperature": 0.0, "seed": 1},
                    timeout=120.0, path="/v1/chat/completions")

            # live streams across a rolling drain-restart
            results = {}
            ths = [threading.Thread(target=self._stream,
                                    args=(gw, results, i))
                   for i in range(3)]
            for t in ths:
                t.start()
            time.sleep(0.3)
            rs.rolling_restart(grace_s=2.0)
            for t in ths:
                t.join(timeout=120)
            assert len(results) == 3
            for finish, usage, acc, err in results.values():
                assert err is None, err
                assert finish in ("stop", "length")
                assert usage["completion_tokens"] >= 1
                assert len(acc) >= 1

            # live streams across a drain-before-kill scale-down
            results = {}
            ths = [threading.Thread(target=self._stream,
                                    args=(gw, results, i))
                   for i in range(3)]
            for t in ths:
                t.start()
            time.sleep(0.3)
            rs.scale_to(1)          # uses the set's drain grace
            for t in ths:
                t.join(timeout=120)
            assert len(rs) == 1
            assert len(results) == 3
            for finish, usage, acc, err in results.values():
                assert err is None, err
                assert finish in ("stop", "length")
                assert usage["completion_tokens"] >= 1
        finally:
            rs.stop()


# --------------------------------------------------------- load generator ----

class TestServingLoadGenerator:
    def test_schedule_is_deterministic_and_tenant_interleaved(self):
        import serving_load
        spec = serving_load.LoadSpec(tenants=3, sessions_per_tenant=2,
                                     turns_per_session=2, seed=5)
        a = serving_load.build_sessions(spec)
        b = serving_load.build_sessions(spec)
        assert a == b
        assert len(a) == spec.total_sessions
        offs = [s["arrival_s"] for s in a]
        assert offs == sorted(offs)
        assert [s["tenant"] for s in a[:3]] == [0, 1, 2]   # interleaved
        c = serving_load.build_sessions(
            serving_load.LoadSpec(tenants=3, sessions_per_tenant=2,
                                  turns_per_session=2, seed=6))
        assert [s["arrival_s"] for s in c] != offs   # seed moves arrivals

    def test_multi_turn_feeds_replies_back(self):
        import serving_load
        spec = serving_load.LoadSpec(tenants=2, sessions_per_tenant=1,
                                     turns_per_session=3, seed=0,
                                     mean_gap_s=0.0)
        seen = []
        lock = threading.Lock()

        def send(messages, meta):
            with lock:
                seen.append([dict(m) for m in messages])
            return f"reply-{meta['tenant']}-{meta['turn']}"

        recs = serving_load.run_load(send, spec, concurrency=2)
        assert len(recs) == spec.total_requests
        assert all(r["ok"] for r in recs)
        turn3 = [m for m in seen if sum(
            1 for x in m if x["role"] == "user") == 3]
        assert turn3   # third turns carry BOTH prior assistant replies
        for msgs in turn3:
            replies = [x["content"] for x in msgs
                       if x["role"] == "assistant"]
            assert len(replies) == 2 and all(
                r.startswith("reply-") for r in replies)

    def test_turn_chars_pads_with_session_unique_filler(self):
        import serving_load
        spec = serving_load.LoadSpec(tenants=2, sessions_per_tenant=2,
                                     turns_per_session=2, seed=0,
                                     turn_chars=300)
        a = serving_load.build_sessions(spec)
        assert a == serving_load.build_sessions(spec)   # deterministic
        turns = [t for s in a for t in s["turns"]]
        assert all(len(t) == 300 for t in turns)
        assert len(set(turns)) == len(turns)   # unique per (t, s, turn)
        # beyond the shared system prompt, no two SESSIONS share a
        # prefix — the padded body is what defeats cross-session
        # prefix-cache aliasing in the soak's pasted-log traffic shape
        first = [s["turns"][0] for s in a]
        for i in range(len(first)):
            for j in range(i + 1, len(first)):
                assert first[i][:80] != first[j][:80]
        # default stays the short shape — existing workloads unchanged
        short = serving_load.user_turn(1, 2, 3)
        assert short == serving_load.user_turn(1, 2, 3, chars=0)
        assert len(short) < 80

    def test_failed_turn_stops_its_session_only(self):
        import serving_load
        spec = serving_load.LoadSpec(tenants=1, sessions_per_tenant=2,
                                     turns_per_session=3, seed=0,
                                     mean_gap_s=0.0)

        def send(messages, meta):
            if meta["session"] == 0 and meta["turn"] == 1:
                raise RuntimeError("boom")
            return "ok"

        recs = serving_load.run_load(send, spec, concurrency=2)
        s0 = [r for r in recs if r["session"] == 0]
        s1 = [r for r in recs if r["session"] == 1]
        assert len(s0) == 2 and not s0[-1]["ok"]   # stopped after failure
        assert len(s1) == 3 and all(r["ok"] for r in s1)


# ---------------------------------------------------------- knob defaults ----

class TestKnobDefaults:
    def test_all_fleet_knobs_default_off(self):
        args = _args()
        assert args.llm_suffix_cache is False
        assert args.serving_cache_aware_routing is False
        assert args.serving_slo_ttft_p99_s == 0.0
        assert args.serving_slo_itl_p99_s == 0.0
        assert args.serving_drain_grace_s == 0.0

    def test_gateway_and_scheduler_defaults_match_pr16(self, setup):
        _, bundle, _, _ = setup
        gw = Gateway(_FakePorts([7001]))
        assert gw.cache_aware is False and gw.heal_probe is False
        sched = _sched(bundle)
        assert sched.suffix_cache is False
        rs = ReplicaSet.__new__(ReplicaSet)
        assert getattr(rs, "drain_grace_s", 0.0) == 0.0
