"""Shared-prefix KV cache + piggybacked prefill (ISSUE 13): refcounted
block aliasing correctness (free never reclaims a shared block while a
reader holds it), copy-on-write never mutating a shared block,
bit-identical decode with the cache on, eviction of cache holders under
KV pressure, wave-prefill parity, compile-once across the new programs,
and the /debug/state + flight-recorder diagnosis surface.
"""

import concurrent.futures as cf

import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.llm.federated import build_llm
from fedml_tpu.llm.kv_cache import (BlockAllocator, KVCacheConfig,
                                    PrefixIndex)
from fedml_tpu.serving.batch import DecodeScheduler
from fedml_tpu.serving.llm_template import CausalLMPredictor

pytestmark = pytest.mark.serving


def _args(**kw):
    base = dict(dataset="llm_synthetic", model="causal_lm",
                client_num_in_total=2, client_num_per_round=2,
                comm_round=1, epochs=1, batch_size=4, learning_rate=1e-3,
                random_seed=3, llm_hidden_size=32, llm_num_layers=2,
                llm_num_heads=2, llm_intermediate_size=64,
                llm_max_seq_len=128, lora_rank=4)
    base.update(kw)
    return Arguments(**base)


@pytest.fixture(scope="module")
def setup():
    import jax
    args = _args()
    _, bundle, _, tok = build_llm(args)
    params = bundle.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))
    return args, bundle, params, tok


def _sched(bundle, **kw):
    opts = dict(slots=4, block_size=8, prefill_chunk=8)
    opts.update(kw)
    return DecodeScheduler(bundle.module, bundle.cfg, bundle.base_params,
                           None, **opts)


def _run(sched, ids, n=6, seed=0, temp=0.0):
    slot, first = sched.admit(ids, seed=seed, temperature=temp,
                              max_new_tokens=n)
    out = [first]
    for _ in range(n - 1):
        out.append(sched.step()[slot])
    sched.release(slot)
    return out


def _enc(tok, p):
    return [1] + tok.encode(p) + [3]


# ------------------------------------------------ allocator refcounts ----

class TestRefcountedAllocator:
    CFG = KVCacheConfig(num_layers=1, kv_heads=1, head_dim=4,
                        max_seq_len=64, block_size=8, num_blocks=16)

    def test_free_never_reclaims_aliased_block_with_live_reader(self):
        """The correctness core: the writer releases, but the aliased
        block must NOT return to the free list while the reader (or the
        prefix index) still references it."""
        alloc = BlockAllocator(self.CFG)
        row_a = alloc.alloc(0, 24)                      # 3 blocks
        shared = [int(b) for b in row_a[:2]]
        alloc.alloc(1, 24, shared=shared)               # aliases 2 of them
        free0 = alloc.free_blocks
        alloc.free(0)                                   # writer releases
        # only the UNshared third block came back
        assert alloc.free_blocks == free0 + 1
        for b in shared:
            assert alloc.refcount(b) == 1               # reader's ref
        alloc.free(1)                                   # reader releases
        assert alloc.free_blocks == self.CFG.num_blocks  # all returned
        assert all(alloc.refcount(b) == 0 for b in shared)

    def test_index_pin_survives_writer_release(self):
        alloc = BlockAllocator(self.CFG)
        row = alloc.alloc(0, 16)
        alloc.retain(int(row[0]))                       # index pin
        alloc.free(0)
        assert alloc.refcount(int(row[0])) == 1         # still resident
        assert alloc.release_block(int(row[0]))         # now it frees

    def test_over_free_raises(self):
        alloc = BlockAllocator(self.CFG)
        row = alloc.alloc(0, 8)
        alloc.free(0)
        with pytest.raises(RuntimeError, match="over-freed"):
            alloc.release_block(int(row[0]))

    def test_alias_of_unreferenced_block_raises(self):
        """A stale prefix-index entry must fail loudly, never silently
        alias a reallocated block's foreign content."""
        alloc = BlockAllocator(self.CFG)
        row = alloc.alloc(0, 8)
        alloc.free(0)
        with pytest.raises(RuntimeError, match="unreferenced"):
            alloc.alloc(1, 16, shared=[int(row[0])])


class TestPrefixIndexHost:
    CFG = KVCacheConfig(num_layers=1, kv_heads=1, head_dim=4,
                        max_seq_len=64, block_size=4, num_blocks=16)

    def test_match_is_exact_token_equality(self):
        alloc = BlockAllocator(self.CFG)
        idx = PrefixIndex(4)
        ids = list(range(10, 22))                       # 3 full blocks
        row = alloc.alloc(0, len(ids))
        idx.insert(ids, row, len(ids), alloc)
        assert idx.match(ids) == [int(b) for b in row[:3]]
        # same first block, divergent second: only the first matches
        div = ids[:4] + [99] * 8
        assert idx.match(div) == [int(row[0])]
        assert idx.match([99] * 12) == []

    def test_cascade_eviction_frees_whole_chain(self):
        alloc = BlockAllocator(self.CFG)
        idx = PrefixIndex(4)
        ids = list(range(10, 22))
        row = alloc.alloc(0, len(ids))
        idx.insert(ids, row, len(ids), alloc)
        alloc.free(0)                                   # index-only pins
        assert alloc.free_blocks == self.CFG.num_blocks - 3
        freed = idx.evict(alloc, self.CFG.num_blocks)
        assert freed == 3 and len(idx) == 0
        assert alloc.free_blocks == self.CFG.num_blocks

    def test_protected_chain_is_skipped(self):
        alloc = BlockAllocator(self.CFG)
        idx = PrefixIndex(4)
        ids = list(range(10, 22))
        row = alloc.alloc(0, len(ids))
        idx.insert(ids, row, len(ids), alloc)
        alloc.free(0)
        idx.evict(alloc, self.CFG.num_blocks,
                  protect=[int(b) for b in row[:3]])    # the matched chain
        # an admission protects its WHOLE matched chain: nothing evicted
        assert len(idx) == 3
        # a protected ROOT alone still shields itself (its subtree
        # intersects the protect set) while unprotected descendants go
        idx.evict(alloc, self.CFG.num_blocks, protect=[int(row[0])])
        assert idx.match(ids) == [int(row[0])]


# ----------------------------------------------------- COW + parity ----

class TestPrefixCacheParity:
    def test_shared_prefix_bit_identical_and_cow_never_mutates(
            self, setup):
        """Two requests sharing a prefix: the second aliases the first's
        blocks (COW for the partial one) and decodes bit-identically to
        the cache-off path; the shared source block's bytes are
        untouched by the second request's prefill + decode."""
        _, bundle, params, tok = setup
        base = _sched(bundle)
        pc = _sched(bundle, prefix_cache=True)
        sys_p = "You are a concise federated assistant. "
        p1 = _enc(tok, sys_p + "first question")
        p2 = _enc(tok, sys_p + "second, longer question entirely")
        ref1, ref2 = _run(base, p1), _run(base, p2)
        assert _run(pc, p1) == ref1                     # cold
        info_miss = pc.last_admit_info
        assert info_miss["cached_tokens"] == 0
        # bytes of the soon-to-be-shared blocks, before the aliasing
        chain = pc._index.match(p2)
        assert chain, "warm lookup found no shared prefix"
        kp_before = np.asarray(pc._kp)[:, chain]
        assert _run(pc, p2) == ref2                     # warm, aliased
        info_hit = pc.last_admit_info
        assert info_hit["cached_tokens"] > 0
        assert info_hit["aliased_blocks"] >= 1
        kp_after = np.asarray(pc._kp)[:, chain]
        assert np.array_equal(kp_before, kp_after), \
            "a shared (read-only) block was mutated"

    def test_cow_partial_block_copy(self, setup):
        """A prompt fully covered by cached blocks forces the cap: the
        last block is COW-copied (bs-1 rows) and exactly one token is
        prefilled — still bit-identical."""
        _, bundle, params, tok = setup
        base = _sched(bundle)
        pc = _sched(bundle, prefix_cache=True)
        p32 = _enc(tok, "y" * 30)                       # 32 = 4 full blocks
        assert len(p32) % 8 == 0
        ref = _run(base, p32)
        assert _run(pc, p32) == ref
        assert _run(pc, p32) == ref                     # warm: COW path
        assert pc.last_admit_info["cow_rows"] == 7
        assert pc.last_admit_info["novel_tokens"] == 1

    def test_sampled_decode_unchanged_by_aliasing(self, setup):
        _, bundle, params, tok = setup
        base = _sched(bundle)
        pc = _sched(bundle, prefix_cache=True)
        p = _enc(tok, "sampling prefix shared across requests q")
        ref = _run(base, p, seed=11, temp=1.3)
        assert _run(pc, p, seed=11, temp=1.3) == ref
        assert _run(pc, p, seed=11, temp=1.3) == ref    # warm

    def test_default_scheduler_has_no_cache_machinery(self, setup):
        _, bundle, params, tok = setup
        s = _sched(bundle)
        assert s._index is None
        assert "prefix_cache" not in s.debug_state()


# ---------------------------------------------------------- eviction ----

class TestEvictionUnderPressure:
    def test_cache_holder_evicted_for_admission(self, setup):
        """KV pressure: a new request that cannot fit alongside the warm
        cache evicts the cold chains (can_admit counts them as
        reclaimable) and admits."""
        _, bundle, params, tok = setup
        pc = _sched(bundle, slots=2, num_blocks=10, prefix_cache=True)
        small = _enc(tok, "cached prompt xyz")          # 19 tok
        _run(pc, small, n=4)
        assert pc._index.cached_blocks == 2             # 2 full blocks
        big = _enc(tok, "B" * 53)                       # 55 tok
        # needs ceil((55 + 9)/8) = 8 blocks; free = 10 - 2 = 8... leave
        # no slack: the pool must evict to fit
        assert pc.can_admit(len(big), 17)               # 72 tok = 9 blocks
        out = _run(pc, big, n=17)
        assert len(out) == 17
        assert pc._index.evictions >= 1

    def test_reader_held_cache_block_survives_eviction(self, setup):
        """Evicting an index entry whose block a live slot aliases drops
        only the index pin — the reader decodes on, bit-identically."""
        _, bundle, params, tok = setup
        base = _sched(bundle)
        pc = _sched(bundle, slots=3, num_blocks=12, prefix_cache=True)
        shared = _enc(tok, "hold this prefix steady ok")   # 28 tok
        ref = _run(base, shared, n=10, seed=5)
        _run(pc, shared, n=10, seed=5)                  # seeds the cache
        slot, first = pc.admit(shared, seed=5, max_new_tokens=10)
        assert pc.last_admit_info["aliased_blocks"] >= 1
        # force eviction pressure while the reader is mid-decode
        big = _enc(tok, "E" * 40)
        slot2, _ = pc.admit(big, max_new_tokens=8)
        out = [first]
        for _ in range(9):
            out.append(pc.step()[slot])
        assert out == ref
        pc.release(slot)
        pc.release(slot2)


# ------------------------------------------- wave prefill + compile ----

class TestPiggybackedPrefill:
    def test_wave_matches_serial_bit_for_bit(self, setup):
        _, bundle, params, tok = setup
        serial = _sched(bundle)
        wave = _sched(bundle, prefix_cache=True, prefill_batch=4)
        prompts = [_enc(tok, p) for p in
                   ("alpha question", "a much longer beta question "
                    "spanning several chunks of prefill", "g",
                    "delta prompt")]
        refs = [_run(serial, p, n=6, seed=i)
                for i, p in enumerate(prompts)]
        pends = [wave.begin_admit(p, seed=i, max_new_tokens=6)
                 for i, p in enumerate(prompts)]
        firsts = wave.finish_admits(pends)
        outs = [[f] for f in firsts]
        for _ in range(5):
            toks = wave.step()
            for i, p in enumerate(pends):
                outs[i].append(toks[p.slot])
        assert outs == refs

    def test_compile_once_across_waves_and_cow(self, setup,
                                               xla_compile_counter):
        """Wave membership, prefix hits, COW copies, eviction churn:
        all DATA — zero recompiles after the three programs warm."""
        _, bundle, params, tok = setup
        sched = _sched(bundle, prefix_cache=True, prefill_batch=4)
        sys_p = "warm system prompt for compile pinning. "
        warm = [_enc(tok, sys_p + s) for s in ("a", "bb long suffix here",
                                               "c", "dd")]
        # warm: serial admit, a full wave, and a COW-triggering repeat
        _run(sched, warm[0], n=3)
        pends = [sched.begin_admit(p, seed=i, max_new_tokens=3)
                 for i, p in enumerate(warm)]
        sched.finish_admits(pends)
        sched.step()
        for p in pends:
            sched.release(p.slot)
        xla_compile_counter.reset()
        for round_i in range(2):
            batch = [_enc(tok, sys_p + f"round {round_i} q {i}")
                     for i in range(3)]
            pends = [sched.begin_admit(p, seed=i, max_new_tokens=3)
                     for i, p in enumerate(batch)]
            assert any(p.info["cached_tokens"] > 0 for p in pends)
            sched.finish_admits(pends)
            for _ in range(2):
                sched.step()
            for p in pends:
                sched.release(p.slot)
        assert xla_compile_counter.delta() == 0


class TestAdmitFailureCleanup:
    def test_failed_prefill_releases_reservation(self, setup,
                                                 monkeypatch):
        """A prefill that raises mid-admit must return the reserved slot
        AND its worst-case block reservation — each transient failure
        must not permanently shrink serving capacity."""
        _, bundle, params, tok = setup
        sched = _sched(bundle, slots=2, prefix_cache=True)
        ids = _enc(tok, "leak probe")
        orig = sched._prefill_serial
        calls = {"n": 0}

        def flaky(p):
            if calls["n"] == 0:
                calls["n"] += 1
                raise RuntimeError("transient device error")
            return orig(p)

        monkeypatch.setattr(sched, "_prefill_serial", flaky)
        with pytest.raises(RuntimeError, match="transient"):
            sched.admit(ids, max_new_tokens=4)
        assert len(sched.free_slots()) == 2          # slot returned
        assert sched.alloc.free_blocks == sched.cache_cfg.num_blocks
        slot, _ = sched.admit(ids, max_new_tokens=4)  # heals
        sched.release(slot)


# -------------------------------------------------- debug + flight ----

class TestDiagnosisSurface:
    def test_debug_state_exposes_index_and_refcounts(self, setup):
        _, bundle, params, tok = setup
        pred = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 8, "prefill_chunk": 8,
                        "prefix_cache": True})
        try:
            pred.generate("debug prefix shared", max_new_tokens=4)
            pred.generate("debug prefix shared too", max_new_tokens=4)
            st = pred.debug_state()["scheduler"]
            pc = st["prefix_cache"]
            assert pc["hits"] >= 1
            assert pc["cached_blocks"] >= 1
            assert pc["block_refcounts"]          # per-block counts live
            assert st["geometry"]["prefix_cache"] is True
            assert st["kv_pool"]["cached_blocks"] >= 1
            # flight records carry the aliased-block count
            admits = [r for r in pred.engine.flight.snapshot()
                      if r["event"] == "admit"]
            assert any(r.get("data", {}).get("aliased_blocks", 0) >= 1
                       for r in admits)
            assert any(r.get("data", {}).get("cached_tokens", 0) > 0
                       for r in admits)
        finally:
            pred.close()

    def test_prefix_metrics_flow_to_registry(self, setup):
        from fedml_tpu.core.obs import metrics as obs_metrics
        _, bundle, params, tok = setup
        pred = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 8, "prefill_chunk": 8,
                        "prefix_cache": True})
        try:
            pred.generate("metric prefix probe", max_new_tokens=3)
            pred.generate("metric prefix probe two", max_new_tokens=3)
            snap = obs_metrics.REGISTRY.snapshot()
            assert "llm_prefix_lookups_total" in snap
            assert "llm_prefix_cached_tokens_total" in snap
            cached = obs_metrics.REGISTRY.counter(
                "llm_prefix_cached_tokens_total").value()
            assert cached > 0
            assert "llm_kv_aliased_blocks" in snap
        finally:
            pred.close()


class TestEngineWaveE2E:
    def test_concurrent_requests_through_wave_engine_match_serial(
            self, setup):
        _, bundle, params, tok = setup
        plain = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 4, "block_size": 8, "prefill_chunk": 8})
        fast = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 4, "block_size": 8, "prefill_chunk": 8,
                        "prefix_cache": True, "prefill_batch": 4})
        try:
            prompts = [f"shared system header. question {i} with tail"
                       for i in range(6)]
            ref = [plain.generate(p, max_new_tokens=6)["text"]
                   for p in prompts]
            with cf.ThreadPoolExecutor(6) as ex:
                got = list(ex.map(
                    lambda p: fast.generate(p, max_new_tokens=6)["text"],
                    prompts))
            assert got == ref
        finally:
            plain.close()
            fast.close()
