"""Chaos subsystem: seeded fault injection + fault-tolerant rounds.

Covers (1) FaultPlan determinism and statistics, (2) the no-op guarantee —
all chaos knobs at defaults leave the simulator bit-identical and the
transport unwrapped, (3) availability faults as data in the jitted round
programs (dropout masking + renormalization, straggler step truncation),
(4) the chaos comm interceptor, the shared backoff helper and the
aggregator's clamped timeout wait, (5) the seeded crash-at-round + resume
e2e through RoundCheckpointer, and (6) the mlops fault ledger.
"""

import threading
import time

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.chaos import (ChaosCommManager, ChaosCrash, FaultLedger,
                                  FaultPlan)

pytestmark = pytest.mark.chaos


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=8, client_num_per_round=8,
                comm_round=3, epochs=1, batch_size=16, learning_rate=0.1,
                frequency_of_the_test=2, random_seed=42)
    base.update(kw)
    return Arguments(**base)


def leaves(tree):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(tree)]


# --- FaultPlan ---------------------------------------------------------------

class TestFaultPlan:
    def test_disabled_by_default(self):
        plan = FaultPlan.from_args(make_args())
        assert not plan.enabled
        assert plan.round_faults(0, range(8)).dropped == ()
        assert plan.work_scale(0, 3) == 1.0
        assert plan.link_decision(0, 1, 0).copies == 1

    def test_same_seed_same_trace(self):
        kw = dict(seed=7, dropout_prob=0.3, straggler_prob=0.2,
                  straggler_work=0.5)
        t1 = FaultPlan(**kw).trace(20, range(16))
        t2 = FaultPlan(**kw).trace(20, range(16))
        assert t1 == t2
        assert any(rf.dropped for rf in t1)
        assert any(rf.work_scale for rf in t1)

    def test_expected_work_fraction(self):
        """dropped -> 0, straggler -> straggler_work, healthy -> 1:
        E[work] = (1 - p_drop) * (1 - p_strag + p_strag * w_strag)."""
        plan = FaultPlan(dropout_prob=0.5, straggler_prob=0.5,
                         straggler_work=0.5)
        assert abs(plan.expected_work_fraction - 0.375) < 1e-12
        assert FaultPlan().expected_work_fraction == 1.0
        # and the empirical trace agrees with the expectation
        fracs = [rf.scale_for(c) for rf in plan.trace(200, range(16))
                 for c in range(16)]
        assert abs(np.mean(fracs) - 0.375) < 0.03

    def test_different_seed_different_trace(self):
        t1 = FaultPlan(seed=1, dropout_prob=0.3).trace(20, range(16))
        t2 = FaultPlan(seed=2, dropout_prob=0.3).trace(20, range(16))
        assert t1 != t2

    def test_queries_are_order_independent(self):
        """Statelessness: server and clients may query in any order and
        must agree — each decision is a pure function of the key."""
        plan = FaultPlan(seed=3, dropout_prob=0.4, straggler_prob=0.3)
        fwd = [plan.work_scale(5, c) for c in range(10)]
        rev = [plan.work_scale(5, c) for c in reversed(range(10))][::-1]
        assert fwd == rev

    def test_dropout_rate_matches_probability(self):
        plan = FaultPlan(seed=0, dropout_prob=0.2)
        hits = sum(plan.is_dropped(r, c)
                   for r in range(50) for c in range(40))
        rate = hits / (50 * 40)
        assert 0.15 < rate < 0.25

    def test_link_decisions_seeded(self):
        kw = dict(seed=5, link_loss_prob=0.3, link_dup_prob=0.3)
        d1 = [FaultPlan(**kw).link_decision(0, 1, s) for s in range(50)]
        d2 = [FaultPlan(**kw).link_decision(0, 1, s) for s in range(50)]
        assert d1 == d2
        assert any(d.copies == 0 for d in d1)
        assert any(d.copies == 2 for d in d1)

    def test_crash_due(self):
        plan = FaultPlan(crash_at_round=4)
        assert plan.enabled
        assert plan.crash_due(4)
        assert not plan.crash_due(3) and not plan.crash_due(5)
        assert not FaultPlan().crash_due(0)


# --- defaults are a no-op ----------------------------------------------------

class TestDefaultsNoOp:
    def test_simulator_bit_identical_with_zeroed_knobs(self):
        """Explicitly-zero chaos knobs and absent knobs must produce the
        SAME jitted program inputs — round outputs bit-identical."""
        r_plain = fedml_tpu.run_simulation(backend="tpu", args=make_args())
        r_zero = fedml_tpu.run_simulation(backend="tpu", args=make_args(
            chaos_dropout_prob=0.0, chaos_straggler_prob=0.0,
            chaos_link_loss_prob=0.0, chaos_over_sample=0.0,
            chaos_tolerance=True))
        for a, b in zip(leaves(r_plain["params"]), leaves(r_zero["params"])):
            assert np.array_equal(a, b)

    def test_tolerance_flag_is_noop_without_faults(self):
        """chaos_tolerance only changes which weights enter the
        denominator; with nobody dropped both variants must agree
        bit-for-bit."""
        r_on = fedml_tpu.run_simulation(backend="tpu",
                                        args=make_args(chaos_tolerance=True))
        r_off = fedml_tpu.run_simulation(backend="tpu",
                                         args=make_args(chaos_tolerance=False))
        for a, b in zip(leaves(r_on["params"]), leaves(r_off["params"])):
            assert np.array_equal(a, b)

    def test_transport_not_wrapped_by_default(self):
        from fedml_tpu.core.distributed.communication.inproc import (
            InProcBroker, InProcCommManager)
        from fedml_tpu.core.distributed.fedml_comm_manager import (
            FedMLCommManager)

        class Mgr(FedMLCommManager):
            pass

        args = make_args(training_type="cross_silo")
        args.inproc_broker = InProcBroker()
        m = Mgr(args, rank=0, size=2, backend="INPROC")
        assert isinstance(m.com_manager, InProcCommManager)
        assert not isinstance(m.com_manager, ChaosCommManager)

    def test_transport_wrapped_when_link_faults_on(self):
        from fedml_tpu.core.distributed.communication.inproc import (
            InProcBroker)
        from fedml_tpu.core.distributed.fedml_comm_manager import (
            FedMLCommManager)

        class Mgr(FedMLCommManager):
            pass

        args = make_args(training_type="cross_silo",
                         chaos_link_loss_prob=0.5)
        args.inproc_broker = InProcBroker()
        m = Mgr(args, rank=0, size=2, backend="INPROC")
        assert isinstance(m.com_manager, ChaosCommManager)


# --- availability faults in the jitted round programs ------------------------

class TestSimulatorFaults:
    def test_all_dropped_round_leaves_params_unchanged(self):
        """With every client dropped (tolerance on), the weighted numerator
        AND denominator are zero — the aggregate update is exactly zero and
        the global model must not move."""
        from fedml_tpu import data as data_mod
        from fedml_tpu import model as model_mod
        from fedml_tpu.core.algframe.client_trainer import (
            ClassificationTrainer)
        from fedml_tpu.core.algframe.types import TrainHyper
        from fedml_tpu.optimizers.registry import create_optimizer
        from fedml_tpu.simulation.tpu.engine import TPUSimulator
        import jax.numpy as jnp

        args = make_args(chaos_dropout_prob=1.0)
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        spec = ClassificationTrainer(bundle.apply)
        opt = create_optimizer(args, spec)
        sim = TPUSimulator(args, fed, bundle, opt, spec)
        before = leaves(sim.params)
        hyper = TrainHyper(learning_rate=jnp.float32(0.1), epochs=1)
        metrics = sim.run_round(0, hyper)
        assert float(metrics["count"]) == 0.0  # nobody reported metrics
        for a, b in zip(before, leaves(sim.params)):
            np.testing.assert_allclose(a, b, rtol=0, atol=0)

    def test_round_cost_flops_scales_with_chaos_work(self):
        """MFU honesty (ISSUE 4 satellite): under dropout/straggler
        injection the costed step count must shrink by the plan's mean
        work fraction — full-schedule costing would overstate MFU."""
        from fedml_tpu import data as data_mod
        from fedml_tpu import model as model_mod
        from fedml_tpu.core.algframe.client_trainer import (
            ClassificationTrainer)
        from fedml_tpu.core.algframe.types import TrainHyper
        from fedml_tpu.optimizers.registry import create_optimizer
        from fedml_tpu.simulation.tpu.engine import TPUSimulator
        import jax.numpy as jnp

        def flops(**kw):
            args = make_args(**kw)
            fed, output_dim = data_mod.load(args)
            bundle = model_mod.create(args, output_dim)
            spec = ClassificationTrainer(bundle.apply)
            sim = TPUSimulator(args, fed, bundle,
                               create_optimizer(args, spec), spec)
            return sim.round_cost_flops(
                TrainHyper(learning_rate=jnp.float32(0.1), epochs=1))

        base = flops()
        injected = flops(chaos_dropout_prob=0.5, chaos_straggler_prob=0.5,
                         chaos_straggler_work=0.5)
        assert base > 0
        # expected fraction: (1 - 0.5) * (0.5 + 0.5 * 0.5) = 0.375
        assert abs(injected / base - 0.375) < 1e-6

    def test_dropout_renormalizes_to_survivor_average(self):
        """Tolerance on: a round with clients {dropped} must equal a round
        where only the survivors were sampled — masking + in-program
        renormalization IS partial participation."""
        from fedml_tpu import data as data_mod
        from fedml_tpu import model as model_mod
        from fedml_tpu.core.algframe.client_trainer import (
            ClassificationTrainer)
        from fedml_tpu.core.algframe.types import TrainHyper
        from fedml_tpu.optimizers.registry import create_optimizer
        from fedml_tpu.simulation.tpu.engine import TPUSimulator
        import jax.numpy as jnp

        args = make_args(chaos_dropout_prob=0.35, random_seed=4,
                         chaos_seed=13)
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        spec = ClassificationTrainer(bundle.apply)
        opt = create_optimizer(args, spec)
        sim = TPUSimulator(args, fed, bundle, opt, spec)
        hyper = TrainHyper(learning_rate=jnp.float32(0.1), epochs=1)
        sampled, (idx, active, work), faults = sim._schedule_for(0)
        assert faults is not None and 0 < len(faults.dropped) < 8
        sim.run_round(0, hyper)
        got = leaves(sim.params)

        # reference: average ONLY the survivors' updates via the SP loop
        sp_args = make_args(random_seed=4)
        fed2, output_dim2 = data_mod.load(sp_args)
        bundle2 = model_mod.create(sp_args, output_dim2)
        spec2 = ClassificationTrainer(bundle2.apply)
        opt2 = create_optimizer(sp_args, spec2)
        from fedml_tpu.core.collectives import tree_weighted_average
        rng = jax.random.PRNGKey(4)
        init_rng, run_rng = jax.random.split(rng)
        params = bundle2.init(init_rng, fed2.train.x[0, 0])
        round_key = jax.random.fold_in(run_rng, 0)
        survivors = [c for c in range(8) if c not in faults.dropped]
        updates, weights = [], []
        for cid in survivors:
            cdata = jax.tree_util.tree_map(lambda a: a[cid], fed2.train)
            key = jax.random.fold_in(round_key, cid)
            out = opt2.local_train(params, opt2.server_init(params),
                                   opt2.client_state_init(params), cdata,
                                   key, hyper.replace(
                                       round_idx=jnp.int32(0)))
            updates.append(out.update)
            weights.append(out.weight)
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *updates)
        agg = tree_weighted_average(stacked, jnp.stack(weights))
        want, _ = opt2.server_update(params, opt2.server_init(params), agg,
                                     {}, jnp.int32(0))
        for a, b in zip(got, leaves(want)):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)

    def test_straggler_truncates_local_steps(self):
        """work_scale rides TrainHyper into the dynamic while_loop: half
        the work fraction must halve the (metrics-visible) step count."""
        from fedml_tpu import data as data_mod
        from fedml_tpu import model as model_mod
        from fedml_tpu.core.algframe.client_trainer import (
            ClassificationTrainer, make_trainer_spec)
        from fedml_tpu.core.algframe.types import TrainHyper
        from fedml_tpu.optimizers.registry import create_optimizer
        import jax.numpy as jnp

        args = make_args()
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        spec = make_trainer_spec(fed, bundle)
        opt = create_optimizer(args, spec)
        rng = jax.random.PRNGKey(0)
        params = bundle.init(rng, fed.train.x[0, 0])
        cdata = jax.tree_util.tree_map(lambda a: a[0], fed.train)
        hyper = TrainHyper(learning_rate=jnp.float32(0.1), epochs=2)
        full = opt.local_train(params, opt.server_init(params),
                               opt.client_state_init(params), cdata, rng,
                               hyper)
        half = opt.local_train(params, opt.server_init(params),
                               opt.client_state_init(params), cdata, rng,
                               hyper.replace(work_scale=jnp.float32(0.5)))
        none = opt.local_train(params, opt.server_init(params),
                               opt.client_state_init(params), cdata, rng,
                               hyper.replace(work_scale=jnp.float32(0.0)))
        n_full = float(full.metrics["count"])
        n_half = float(half.metrics["count"])
        assert 0 < n_half < n_full
        assert abs(n_half - n_full / 2) <= n_full / 8  # ~half the steps
        assert float(none.metrics["count"]) == 0.0     # dropped: no steps
        for a, b in zip(leaves(none.update), leaves(params)):
            assert np.all(a == 0)  # zero steps -> zero update

    def test_chaos_run_learns_and_fused_path_used(self):
        """20% dropout + 10% stragglers with tolerance on: the fused
        multi-round dispatch still runs (faults are data) and the model
        still learns."""
        r = fedml_tpu.run_simulation(backend="tpu", args=make_args(
            comm_round=6, chaos_dropout_prob=0.2,
            chaos_straggler_prob=0.1))
        assert r["final_test_acc"] > 0.5

    def test_over_sampling_enlarges_cohort(self):
        from fedml_tpu import data as data_mod
        from fedml_tpu import model as model_mod
        from fedml_tpu.core.algframe.client_trainer import (
            ClassificationTrainer)
        from fedml_tpu.optimizers.registry import create_optimizer
        from fedml_tpu.simulation.tpu.engine import TPUSimulator

        args = make_args(client_num_in_total=16, client_num_per_round=8,
                         chaos_over_sample=0.25, chaos_dropout_prob=0.2)
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        spec = ClassificationTrainer(bundle.apply)
        opt = create_optimizer(args, spec)
        sim = TPUSimulator(args, fed, bundle, opt, spec)
        assert sim._sample_n == 10  # ceil(8 * 1.25)
        sampled, _, _ = sim._schedule_for(0)
        assert len(sampled) == 10


# --- crash-at-round + resume e2e --------------------------------------------

def _ckpt_args(tmp, **kw):
    base = dict(comm_round=6, checkpoint_dir=str(tmp),
                checkpoint_every_rounds=2, frequency_of_the_test=3,
                random_seed=11)
    base.update(kw)
    return make_args(**base)


def test_crash_resume_reaches_uninterrupted_accuracy(tmp_path):
    """Seeded crash at round 3 (after its checkpoint lands) + resume must
    reproduce the uninterrupted run's final params exactly — determinism
    makes elastic recovery testable."""
    full = fedml_tpu.run_simulation(
        backend="tpu", args=_ckpt_args(tmp_path / "full"))
    crash_dir = tmp_path / "crash"
    with pytest.raises(ChaosCrash) as ei:
        fedml_tpu.run_simulation(
            backend="tpu", args=_ckpt_args(crash_dir,
                                           chaos_crash_at_round=3))
    assert ei.value.round_idx == 3
    # resume with the SAME args: the crash round's checkpoint was flushed
    # before raising, so the restored trajectory starts past it and the
    # crash does not re-fire
    resumed = fedml_tpu.run_simulation(
        backend="tpu", args=_ckpt_args(crash_dir, chaos_crash_at_round=3))
    assert resumed["final_test_acc"] is not None
    for a, b in zip(leaves(full["params"]), leaves(resumed["params"])):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_crash_resume_soak_with_dropout(tmp_path):
    """Long variant: crash + resume under 20% dropout and stragglers, SP
    cross-check of the final accuracy band."""
    kw = dict(comm_round=12, chaos_dropout_prob=0.2,
              chaos_straggler_prob=0.1, checkpoint_every_rounds=3,
              frequency_of_the_test=4)
    full = fedml_tpu.run_simulation(
        backend="tpu", args=_ckpt_args(tmp_path / "full", **kw))
    crash_dir = tmp_path / "crash"
    with pytest.raises(ChaosCrash):
        fedml_tpu.run_simulation(
            backend="tpu", args=_ckpt_args(crash_dir,
                                           chaos_crash_at_round=5, **kw))
    resumed = fedml_tpu.run_simulation(
        backend="tpu", args=_ckpt_args(crash_dir, **kw))
    for a, b in zip(leaves(full["params"]), leaves(resumed["params"])):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    assert abs(full["final_test_acc"] - resumed["final_test_acc"]) < 1e-6


# --- async checkpoints + donation -------------------------------------------

def test_async_checkpoint_snapshots_before_donation(tmp_path):
    """The save must copy state to host BEFORE the next round program
    donates (and overwrites) the buffers: the checkpoint written at round
    k must restore round-k params even though rounds k+1.. donated and
    replaced them in HBM."""
    from fedml_tpu import data as data_mod
    from fedml_tpu import model as model_mod
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator
    import jax.numpy as jnp

    args = make_args(donate_buffers=True, checkpoint_dir=str(tmp_path),
                     checkpoint_every_rounds=2)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    opt = create_optimizer(args, spec)
    sim = TPUSimulator(args, fed, bundle, opt, spec)
    hyper = TrainHyper(learning_rate=jnp.float32(0.1), epochs=1)
    sim.run_round(0, hyper)
    sim.run_round(1, hyper)
    at_save = leaves(sim.params)
    assert sim.ckpt.maybe_save(1, sim._ckpt_state())
    # keep training: the donated round-1 buffers are gone from HBM
    sim.run_round(2, hyper)
    sim.run_round(3, hyper)
    restored = sim.ckpt.latest(sim._ckpt_state())
    assert restored is not None and restored[0] == 1
    for a, b in zip(at_save, leaves(restored[1]["params"])):
        np.testing.assert_allclose(a, b, rtol=0, atol=0)


# --- interceptor, backoff, aggregator clamp ---------------------------------

class _CaptureComm:
    def __init__(self):
        self.sent = []
        self.observers = []

    def send_message(self, msg):
        self.sent.append((time.monotonic(), msg))

    def add_observer(self, obs):
        self.observers.append(obs)

    def remove_observer(self, obs):
        pass

    def notify(self, msg):
        pass

    def handle_receive_message(self):
        pass

    def stop_receive_message(self):
        pass


class TestInterceptor:
    def _msg(self, receiver=1):
        from fedml_tpu.core.distributed.communication.message import Message
        return Message(7, 0, receiver)

    def test_loss_drops_messages(self):
        inner = _CaptureComm()
        cm = ChaosCommManager(inner, FaultPlan(seed=1, link_loss_prob=1.0),
                              rank=0)
        for _ in range(5):
            cm.send_message(self._msg())
        assert inner.sent == []
        assert len(cm.ledger.links()) == 5

    def test_duplication_sends_twice(self):
        inner = _CaptureComm()
        cm = ChaosCommManager(inner, FaultPlan(seed=1, link_dup_prob=1.0),
                              rank=0)
        cm.send_message(self._msg())
        assert len(inner.sent) == 2

    def test_delay_defers_delivery(self):
        inner = _CaptureComm()
        cm = ChaosCommManager(
            inner, FaultPlan(seed=1, link_delay_prob=1.0,
                             link_delay_s=0.15), rank=0)
        t0 = time.monotonic()
        cm.send_message(self._msg())
        assert inner.sent == []  # not delivered synchronously
        deadline = time.monotonic() + 3.0
        while not inner.sent and time.monotonic() < deadline:
            time.sleep(0.01)
        assert inner.sent and inner.sent[0][0] - t0 >= 0.1

    def test_clean_plan_passes_through(self):
        inner = _CaptureComm()
        cm = ChaosCommManager(inner, FaultPlan(seed=1, link_loss_prob=0.0),
                              rank=0)
        m = self._msg()
        cm.send_message(m)
        assert inner.sent[0][1] is m
        assert cm.ledger.links() == []


class TestBackoff:
    def test_delays_grow_and_cap(self):
        from fedml_tpu.core.distributed.communication.backoff import (
            backoff_delays)
        it = backoff_delays(0.1, 2.0, 0.8, jitter=False)
        ds = [next(it) for _ in range(6)]
        assert ds == [0.1, 0.2, 0.4, 0.8, 0.8, 0.8]

    def test_jitter_bounded_and_seeded(self):
        from fedml_tpu.core.distributed.communication.backoff import (
            backoff_delays)
        it_a = backoff_delays(0.2, 2.0, 2.0, seed=9)
        it_b = backoff_delays(0.2, 2.0, 2.0, seed=9)
        a = [next(it_a) for _ in range(8)]
        b = [next(it_b) for _ in range(8)]
        assert a == b
        caps = [0.2, 0.4, 0.8, 1.6, 2.0, 2.0, 2.0, 2.0]
        assert all(0.0 <= d <= c for d, c in zip(a, caps))

    def test_retry_succeeds_after_failures(self):
        from fedml_tpu.core.distributed.communication.backoff import (
            retry_with_backoff)
        calls = {"n": 0}
        slept = []

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("refused")
            return "ok"

        out = retry_with_backoff(flaky, max_attempts=4, base_s=0.01,
                                 max_s=0.02, retry_on=(OSError,),
                                 sleep=slept.append)
        assert out == "ok" and calls["n"] == 3 and len(slept) == 2

    def test_retry_exhausts_and_raises(self):
        from fedml_tpu.core.distributed.communication.backoff import (
            retry_with_backoff)

        def always():
            raise OSError("down")

        with pytest.raises(OSError):
            retry_with_backoff(always, max_attempts=2, base_s=0.01,
                               max_s=0.01, retry_on=(OSError,),
                               sleep=lambda d: None)

    def test_zero_attempts_fails_fast(self):
        from fedml_tpu.core.distributed.communication.backoff import (
            retry_with_backoff)
        calls = {"n": 0}

        def boom():
            calls["n"] += 1
            raise OSError("x")

        with pytest.raises(OSError):
            retry_with_backoff(boom, max_attempts=0, retry_on=(OSError,),
                               sleep=lambda d: None)
        assert calls["n"] == 1


class TestAggregatorTimeout:
    def test_clamped_wait_regression(self):
        """The old inline expression `min(remaining or 1.0, 1.0)` waited a
        FULL second for remaining == 0.0 (falsy!) and passed negative
        timeouts through on underflow; the clamp pins both."""
        from fedml_tpu.cross_silo.server.fedml_aggregator import clamped_wait
        assert clamped_wait(0.0) == 0.05          # not 1.0
        assert clamped_wait(-3.0) == 0.05         # not negative
        assert clamped_wait(0.5) == 0.5
        assert clamped_wait(10.0) == 1.0
        assert clamped_wait(None) == 1.0

    def _agg(self, timeout, quorum_frac=0.0, expected=2):
        from fedml_tpu.cross_silo.server.fedml_aggregator import (
            FedMLAggregator)
        args = make_args(client_num_per_round=expected,
                         round_timeout_s=timeout,
                         round_quorum_frac=quorum_frac,
                         training_type="cross_silo")
        params = {"w": np.zeros((2,), np.float32)}
        return FedMLAggregator(args, params)

    def test_timeout_returns_promptly_with_partial_reports(self):
        agg = self._agg(0.3)
        agg.add_local_trained_result(1, {"w": np.ones((2,), np.float32)},
                                     1.0)
        t0 = time.monotonic()
        assert agg.wait_all_or_timeout() is True
        assert time.monotonic() - t0 < 1.0  # deadline 0.3 + clamp margin

    def test_full_cohort_returns_immediately(self):
        agg = self._agg(30.0)
        for i in (1, 2):
            agg.add_local_trained_result(
                i, {"w": np.ones((2,), np.float32)}, 1.0)
        t0 = time.monotonic()
        assert agg.wait_all_or_timeout() is True
        assert time.monotonic() - t0 < 0.1

    def test_below_quorum_waits_for_late_report(self):
        """quorum 2 of 2: one report at the deadline is not enough — the
        grace interval must pick up the straggler instead of averaging a
        sliver."""
        agg = self._agg(0.3, quorum_frac=1.0)
        agg.add_local_trained_result(1, {"w": np.ones((2,), np.float32)},
                                     1.0)

        def late():
            time.sleep(0.45)
            agg.add_local_trained_result(
                2, {"w": np.ones((2,), np.float32)}, 1.0)

        threading.Thread(target=late, daemon=True).start()
        t0 = time.monotonic()
        assert agg.wait_all_or_timeout() is True
        dt = time.monotonic() - t0
        assert 0.3 < dt < 2.0
        assert len(agg.model_dict) == 2

    def test_zero_reports_gives_up_after_grace(self):
        agg = self._agg(0.2)
        t0 = time.monotonic()
        assert agg.wait_all_or_timeout() is False
        assert 0.3 < time.monotonic() - t0 < 2.0


# --- fault ledger ------------------------------------------------------------

def test_engine_ledger_reconciles_injected_and_observed(tmp_path):
    from fedml_tpu import data as data_mod
    from fedml_tpu import model as model_mod
    from fedml_tpu.core import mlops
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator
    import jax.numpy as jnp
    import json

    args = make_args(chaos_dropout_prob=0.3, chaos_straggler_prob=0.2,
                     run_id="chaos_ledger_test",
                     log_file_dir=str(tmp_path))
    mlops.init(args)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    opt = create_optimizer(args, spec)
    sim = TPUSimulator(args, fed, bundle, opt, spec)
    hyper = TrainHyper(learning_rate=jnp.float32(0.1), epochs=1)
    for r in range(4):
        sim.run_round(r, hyper)
    recs = sim.chaos_ledger.rounds()
    assert len(recs) == 4
    for rec in recs:
        inj, obs = rec["injected"], rec["observed"]
        # the program observed exactly sampled - injected-dropped slots
        assert obs["participating"] == obs["sampled"] - len(inj["dropped"])
    # mirrored to the mlops sink
    lines = [json.loads(l) for l in
             open(tmp_path / "run_chaos_ledger_test.jsonl")]
    chaos_recs = [l for l in lines if l.get("kind") == "chaos"]
    assert len(chaos_recs) >= 4
    mlops.init(make_args(enable_tracking=False))  # detach the sink


# --- chaos for the hierarchical and decentralized paths ----------------------
# (ROADMAP leftover closed in ISSUE 5: the link-fault interceptor wraps
# every FedMLCommManager subclass, and the gossip runtime retransmits
# through injected loss via the shared backoff helper)

class TestChaosHierarchicalAndDecentralized:
    def test_gossip_session_survives_link_loss(self):
        """Decentralized gossip has no server to time a round out — a lost
        N2N_PARAMS frame used to deadlock both endpoints. The resend loop
        (backoff-paced, idempotent receivers) must carry the session
        through seeded loss + duplication."""
        from fedml_tpu import data as data_mod, model as model_mod
        from fedml_tpu.cross_silo.decentralized import run_gossip_inproc

        args = make_args(
            training_type="cross_silo", client_num_in_total=4,
            client_num_per_round=4, comm_round=3, topology_neighbors=2,
            chaos_link_loss_prob=0.15, chaos_link_dup_prob=0.1,
            chaos_seed=13)
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        result = run_gossip_inproc(args, fed, bundle)
        assert result is not None, "gossip session stalled under link loss"
        assert result["rounds"] == 3
        assert result["final_test_acc"] > 0.5

    def test_gossip_resend_loop_off_without_link_faults(self):
        """Without link-fault knobs the gossip node must not start the
        resend machinery (default path unchanged)."""
        from fedml_tpu import data as data_mod, model as model_mod
        from fedml_tpu.cross_silo.decentralized import GossipNodeManager
        from fedml_tpu.core.distributed.communication.inproc import (
            InProcBroker)

        args = make_args(training_type="cross_silo",
                         client_num_in_total=3, client_num_per_round=3)
        args.inproc_broker = InProcBroker()
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        node = GossipNodeManager(args, fed, bundle, rank=0, size=3,
                                 backend="INPROC")
        assert not node.chaos_plan.injects_link_faults
        assert not isinstance(node.com_manager, ChaosCommManager)
        node.com_manager.stop_receive_message()

    def test_hierarchical_session_survives_link_loss(self):
        """Hierarchical silos ride the same ClientMasterManager FSM: the
        interceptor wraps their transports, and round timeout + quorum +
        the ONLINE re-announce carry the session through injected loss."""
        from fedml_tpu import data as data_mod, model as model_mod
        from fedml_tpu.core.chaos import ChaosCommManager as CCM
        from fedml_tpu.cross_silo.hierarchical.runner import (
            run_hierarchical_cross_silo_inproc)

        args = make_args(
            training_type="cross_silo", client_num_in_total=4,
            client_num_per_round=2, comm_round=2, round_timeout_s=20.0,
            chaos_link_loss_prob=0.1, chaos_link_dup_prob=0.1,
            chaos_seed=17)
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        result = run_hierarchical_cross_silo_inproc(args, fed, bundle)
        assert result is not None, "hierarchical session stalled"
        assert len(result["history"]) == 2
