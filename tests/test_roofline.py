"""Compute-plane observability (core/obs/roofline): per-op roofline
attribution, collective-traffic accounting, recompile forensics, and the
``scripts/roofline_report.py`` CLI.

Pins: analytical FLOPs/bytes are EXACT on hand-computable programs
(matmul, psum), while-loop trip counts multiply scanned bodies, the
``kind: roofline`` / ``kind: recompile`` records validate against the
schema on a REAL engine run, capture costs zero compiles at default
knobs, and a forced recompile's forensics record names the changed
abstract shape.
"""

import glob
import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.obs


def _mk(**kw):
    from fedml_tpu.arguments import Arguments
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=8, client_num_per_round=8,
                comm_round=2, epochs=1, batch_size=16, learning_rate=0.1,
                frequency_of_the_test=100, random_seed=0)
    base.update(kw)
    return Arguments(**base)


def _build_sim(args):
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator
    fed, od = load(args)
    bundle = create(args, od)
    spec = ClassificationTrainer(bundle.apply)
    return TPUSimulator(args, fed, bundle, create_optimizer(args, spec),
                        spec)


def _hyper(args):
    import jax.numpy as jnp
    from fedml_tpu.core.algframe.types import TrainHyper
    return TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                      epochs=1)


# ---------------------------------------------------------------------------
class TestCostModel:
    def test_matmul_flops_and_bytes_exact(self):
        """2*M*N*K flops, operands+output bytes — the hand check."""
        import jax
        import jax.numpy as jnp
        from fedml_tpu.core.obs import roofline
        f = jax.jit(lambda a, b: jnp.dot(a, b))
        co = f.lower(jnp.ones((8, 16)), jnp.ones((16, 4))).compile()
        rec = roofline.analyze_compiled("mm", co, n_devices=1)
        assert rec["total_flops"] == 2 * 8 * 16 * 4
        assert rec["total_bytes"] == 4 * (8 * 16 + 16 * 4 + 8 * 4)
        top = rec["ops"][0]
        assert top["op"] == "dot"
        assert top["operands"] == ["f32[8,16]", "f32[16,4]"]
        assert rec["attributed_share"] == 1.0
        # 1024 flops / 896 bytes is far under any machine balance
        assert top["bound"] == "memory"

    def test_psum_collective_wire_bytes_exact(self):
        """all-reduce over the 8-device CPU mesh: ring traffic is
        2*(g-1)/g * payload per device, group parsed from the HLO."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P
        from fedml_tpu.core.jax_compat import shard_map
        from fedml_tpu.core.obs import roofline
        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs the multi-device CPU mesh")
        mesh = Mesh(np.array(devs), ("d",))

        def body(x, w):
            return jax.lax.psum(x @ w, "d")

        f = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("d"), P()),
                              out_specs=P()))
        co = f.lower(jnp.ones((4 * len(devs), 16)),
                     jnp.ones((16, 8))).compile()
        rec = roofline.analyze_compiled("psum", co, n_devices=len(devs))
        colls = rec["collectives"]
        assert len(colls) == 1 and colls[0]["op"] == "all-reduce"
        g = len(devs)
        assert colls[0]["group"] == g
        payload = 4 * 4 * 8     # f32[4,8] per-device partial
        want = 2.0 * (g - 1) / g * payload
        assert rec["collective_wire_bytes"] == pytest.approx(want)
        # per-device dot: 2 * 4 * 16 * 8, plus the reduce adds
        assert rec["total_flops"] >= 2 * 4 * 16 * 8

    def test_scan_trip_count_multiplies_body(self):
        """A lax.scan body attributes trip_count x its per-iteration
        cost (XLA's known_trip_count or the parsed loop bound)."""
        import jax
        import jax.numpy as jnp
        from fedml_tpu.core.obs import roofline

        def run(c, xs):
            return jax.lax.scan(lambda c, x: (c * 1.5 + x, jnp.float32(0)),
                                c, xs)[0]

        co = jax.jit(run).lower(jnp.ones((64,)),
                                jnp.ones((7, 64))).compile()
        rec = roofline.analyze_compiled("scan", co, n_devices=1)
        body_rows = [r for r in rec["ops"] if r["mult"] == 7]
        assert body_rows, rec["ops"]
        # c*1.5 + x = 2 flops/element * 64 * 7 iterations
        assert rec["total_flops"] >= 2 * 64 * 7

    def test_window_reads_charged_the_window(self):
        """A fused dynamic-slice of a big stacked array is charged the
        slice, not the stack — otherwise per-slot data slicing would
        drown the compute it feeds."""
        import jax
        import jax.numpy as jnp
        from fedml_tpu.core.obs import roofline

        big = jnp.ones((64, 256))

        def run(big, i):
            return jnp.sum(jax.lax.dynamic_slice_in_dim(big, i, 1) * 2.0)

        co = jax.jit(run).lower(big, jnp.int32(3)).compile()
        rec = roofline.analyze_compiled("slice", co, n_devices=1)
        # full stack = 64 KiB; the window is one 1 KiB row (+ output)
        assert rec["total_bytes"] < 64 * 256 * 4 / 2

    def test_machine_balance_table_is_total(self):
        """Every peak-TFLOPs device kind has an HBM entry, and a CPU
        balance is static-only while a TPU one is not."""
        from fedml_tpu.core.obs import profiler, roofline

        class Dev:
            def __init__(self, kind):
                self.device_kind = kind

        for key, _peak in profiler.PEAK_TFLOPS_BF16:
            assert roofline.hbm_gbps(Dev(key)) is not None, key
        cpu = roofline.machine_balance(Dev("cpu"))
        assert cpu.static_only and cpu.flops_per_byte is not None
        v4 = roofline.machine_balance(Dev("TPU v4"))
        assert not v4.static_only
        assert v4.peak_tflops == 275.0 and v4.hbm_gbps == 1228.0


# ---------------------------------------------------------------------------
class TestEngineCapture:
    def test_engine_run_emits_schema_valid_roofline_records(
            self, tmp_path, xla_compile_counter):
        """Real engine run with obs_roofline: every JSONL line validates
        (the replay gate for the new kinds), the round program's record
        attributes >=90% of predicted time, and the dispatch records
        still report exactly one compile (the AOT capture is not charged
        to the dispatch)."""
        from fedml_tpu.core import mlops
        from fedml_tpu.core.obs import roofline, schema
        args = _mk(obs_roofline=True, log_file_dir=str(tmp_path))
        mlops.init(args)
        sim = _build_sim(args)
        hyper = _hyper(args)
        sim.run_round(0, hyper)
        sim.run_round(1, hyper)
        assert sim.dispatch_stats["compiles"] == 1

        rep = roofline.report("round")
        assert rep is not None
        assert rep["attributed_share"] >= 0.9
        assert rep["static_only"] is True      # CPU mesh: loud, flagged
        assert rep["ops"] and rep["total_flops"] > 0
        logs = glob.glob(str(tmp_path / "**" / "*.jsonl"), recursive=True)
        assert logs
        kinds = set()
        for p in logs:
            with open(p) as f:
                lines = f.readlines()
            assert schema.validate_lines(lines) == []
            for line in lines:
                if line.strip():
                    kinds.add(json.loads(line).get("kind"))
        assert "roofline" in kinds

        from fedml_tpu.core.obs.metrics import REGISTRY
        g = REGISTRY.gauge("roofline_predicted_mfu", labels=("program",))
        assert g.value(program="round") is not None

    def test_default_knobs_capture_nothing_and_compile_nothing(
            self, tmp_path, xla_compile_counter):
        """obs_roofline off (default): no roofline records, no extra
        compiles — the compile-once invariant is untouched."""
        from fedml_tpu.core import mlops
        from fedml_tpu.core.obs import roofline
        args = _mk(log_file_dir=str(tmp_path))
        mlops.init(args)
        sim = _build_sim(args)
        assert sim._roofline.enabled is False
        hyper = _hyper(args)
        sim.run_round(0, hyper)
        xla_compile_counter.reset()
        sim.run_round(1, hyper)
        assert xla_compile_counter.delta() == 0
        assert sim.dispatch_stats["compiles"] == 1
        for p in glob.glob(str(tmp_path / "**" / "*.jsonl"),
                           recursive=True):
            with open(p) as f:
                assert not any('"kind": "roofline"' in ln for ln in f)


# ---------------------------------------------------------------------------
class TestRecompileForensics:
    def test_forced_recompile_names_the_changed_shape(self, tmp_path):
        """A real jitted program re-dispatched at a new abstract shape:
        the forensics record names the leaf and the old -> new shape,
        and validates against the schema."""
        import jax
        import jax.numpy as jnp
        from fedml_tpu.core import mlops
        from fedml_tpu.core.obs import roofline, schema
        mlops.init(_mk(log_file_dir=str(tmp_path)))
        mlops.install_compile_counter()
        tracker = roofline.DispatchTracker(enabled=False)
        f = jax.jit(lambda x: x * 2.0)
        recs = []
        for shape in ((4,), (8,)):
            x = jnp.zeros(shape)
            sig = roofline.dispatch_signature((x,))
            c0 = mlops.compile_count()
            f(x)
            recs.append(tracker.observe("prog", sig,
                                        mlops.compile_count() - c0))
        assert recs[0] is None          # first compile: pinned expectation
        rec = recs[1]
        assert rec is not None and rec["program"] == "prog"
        assert rec["changed"], rec
        ch = rec["changed"][0]
        assert "4" in ch["was"] and "8" in ch["now"]
        assert schema.validate_record({**rec, "kind": "recompile",
                                       "ts": 0.0, "run_id": "t"}) == []
        assert rec in roofline.recent_recompiles()

    def test_engine_seam_emits_forensics_on_width_change(self, tmp_path):
        """Dispatch the engine's real round program at a widened
        schedule: the recompile record lands in the run log naming the
        schedule leaves that moved."""
        import jax
        import jax.numpy as jnp
        from fedml_tpu.core import mlops
        args = _mk(log_file_dir=str(tmp_path))
        mlops.init(args)
        sim = _build_sim(args)
        hyper = _hyper(args)
        sim.run_round(0, hyper)

        # re-dispatch with every schedule tensor one slot wider (the
        # padded slot is inactive, so semantics are unchanged — only
        # the abstract shape moves)
        sampled, (idx, active, work), _ = sim._schedule_for(1)
        pad = ((0, 0), (0, 1))
        idx = jax.device_put(jnp.asarray(np.pad(idx, pad)),
                             sim.client_sharding)
        active = jax.device_put(jnp.asarray(np.pad(active, pad)),
                                sim.client_sharding)
        work = jax.device_put(jnp.asarray(np.pad(work, pad)),
                              sim.client_sharding)
        key = jax.random.fold_in(sim.rng, 1)
        sim._traced("round", 1, sim._round_fn, sim.params,
                    sim.server_state, sim.train_data, sim.client_states,
                    idx, active, work, key,
                    hyper.replace(round_idx=jnp.int32(1)))
        recs = []
        for p in glob.glob(str(tmp_path / "**" / "*.jsonl"),
                           recursive=True):
            with open(p) as f:
                recs += [json.loads(ln) for ln in f if ln.strip()]
        forensics = [r for r in recs if r.get("kind") == "recompile"]
        assert forensics, "no recompile record emitted"
        rec = forensics[-1]
        assert rec["program"] == "round"
        changed_args = " ".join(c["arg"] for c in rec["changed"])
        assert "[4]" in changed_args or "[5]" in changed_args \
            or "[6]" in changed_args or rec["changed"]

    def test_compile_delta_repr_carries_forensics(self):
        """The conftest counter's failing delta prints the forensics —
        every existing compile-once test upgrades for free."""
        from tests.conftest import _CompileDelta
        from fedml_tpu.core.obs import roofline
        roofline._recent_recompiles.append(
            {"program": "demo", "compiles": 1, "total_compiles": 2,
             "expected": 1,
             "changed": [{"arg": "[0]", "was": "f32[4]",
                          "now": "f32[8]"}], "note": None})
        try:
            assert repr(_CompileDelta(0)) == "0"
            r = repr(_CompileDelta(1))
            assert "demo" in r and "f32[4]" in r and "f32[8]" in r
        finally:
            roofline._recent_recompiles.pop()


# ---------------------------------------------------------------------------
class TestServingCapture:
    def test_decode_and_prefill_programs_capture(self):
        """The serving scheduler's dispatch seam captures the decode
        step and prefill programs when the module default is on."""
        import jax
        from fedml_tpu.arguments import Arguments
        from fedml_tpu.llm.federated import build_llm
        from fedml_tpu.serving.batch import DecodeScheduler
        from fedml_tpu.core.obs import roofline
        args = Arguments(
            dataset="llm_synthetic", model="causal_lm",
            client_num_in_total=2, client_num_per_round=2, comm_round=1,
            epochs=1, batch_size=4, learning_rate=1e-3, random_seed=3,
            llm_hidden_size=32, llm_num_layers=2, llm_num_heads=2,
            llm_intermediate_size=64, llm_max_seq_len=64, lora_rank=4)
        _, bundle, _, tok = build_llm(args)
        roofline.set_default_enabled(True)
        try:
            sched = DecodeScheduler(bundle.module, bundle.cfg,
                                    bundle.base_params, None, slots=2,
                                    block_size=16, prefill_chunk=8)
            ids = [1] + tok.encode("roofline capture") + [3]
            slot, _ = sched.admit(ids, max_new_tokens=2)
            sched.step()
            sched.release(slot)
        finally:
            roofline.set_default_enabled(False)
        for prog in ("llm_decode_step", "llm_prefill_chunk"):
            rep = roofline.report(prog)
            assert rep is not None, prog
            assert rep["total_flops"] > 0
            assert rep["attributed_share"] >= 0.9


# ---------------------------------------------------------------------------
class TestReportCLI:
    def _write_log(self, path, attributed=1.0):
        rec = {"kind": "roofline", "ts": 0.0, "run_id": "t",
               "program": "round", "device_kind": "cpu", "n_devices": 8,
               "static_only": True, "peak_tflops": 0.5, "hbm_gbps": 25.0,
               "balance_flops_per_byte": 20.0,
               "total_flops": 2.0e9, "total_bytes": 1.0e8,
               "predicted_s": 0.004, "predicted_mfu": 0.069,
               "attributed_share": attributed,
               "memory_bound_share": 0.82, "compute_bound_share": 0.18,
               "collective_wire_bytes": 1792.0,
               "xla_flops": None, "xla_bytes": None,
               "ops": [
                   {"name": "convolution.1", "op": "convolution",
                    "op_name": "conv_general_dilated", "out": "f32[32,8,8,64]",
                    "operands": ["f32[32,8,8,64]", "f32[3,3,64,64]"],
                    "flops": 1.9e9, "bytes": 5.0e7, "mult": 30,
                    "intensity": 38.0, "bound": "memory",
                    "time_s": 0.002, "share": 0.5, "estimated": False},
                   {"name": "fusion.2", "op": "fusion", "op_name": "relu",
                    "out": "f32[32,8,8,64]",
                    "operands": ["f32[32,8,8,64]"],
                    "flops": 1.0e8, "bytes": 5.0e7, "mult": 30,
                    "intensity": 2.0, "bound": "memory",
                    "time_s": 0.002, "share": 0.5, "estimated": False}],
               "collectives": [
                   {"op": "all-reduce", "operands": ["f32[256]"],
                    "group": 8, "count": 1, "payload_bytes": 1024.0,
                    "wire_bytes": 1792.0}]}
        fore = {"kind": "recompile", "ts": 0.0, "run_id": "t",
                "program": "round", "compiles": 1, "total_compiles": 2,
                "expected": 1,
                "changed": [{"arg": "[4]", "was": "s32[8,2]",
                             "now": "s32[8,4]"}], "note": None}
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
            f.write(json.dumps(fore) + "\n")

    def test_report_golden_sections(self, tmp_path, capsys):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "scripts"))
        import roofline_report
        log = str(tmp_path / "run.jsonl")
        self._write_log(log)
        rc = roofline_report.main([log, "--top", "4"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "== round — cpu x8" in out
        assert "STATIC-ONLY" in out
        assert "convolution(f32[32,8,8,64],f32[3,3,64,64])" in out
        assert "memory 82.0%" in out
        assert "all-reduce" in out and "1.79kB" in out
        assert "recompile forensics" in out
        assert "s32[8,2] -> s32[8,4]" in out

    def test_min_attr_gate(self, tmp_path, capsys):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "scripts"))
        import roofline_report
        log = str(tmp_path / "run.jsonl")
        self._write_log(log, attributed=0.7)
        assert roofline_report.main([log, "--min-attr", "0.9"]) == 2
        capsys.readouterr()
        self._write_log(log, attributed=0.95)
        assert roofline_report.main([log, "--min-attr", "0.9"]) == 0
        assert "coverage OK" in capsys.readouterr().out

    def test_compare_mode(self, tmp_path, capsys):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "scripts"))
        import roofline_report
        a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
        self._write_log(a)
        self._write_log(b)
        assert roofline_report.main([a, "--compare", b]) == 0
        out = capsys.readouterr().out
        assert "predicted_mfu" in out and "collective_wire_bytes" in out


# ---------------------------------------------------------------------------
class TestBenchDiffMarkers:
    def test_roofline_metric_directions(self):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "scripts"))
        import bench_diff
        assert not bench_diff.lower_is_better("roofline_predicted_mfu")
        assert bench_diff.lower_is_better("memory_bound_share")
        assert bench_diff.lower_is_better("recompiles")
        assert bench_diff.lower_is_better("collective_wire_bytes")
        assert not bench_diff.lower_is_better(
            "fedavg_robust_rfa_weak_scaling_efficiency")
        assert not bench_diff.lower_is_better(
            "llm_serving_adapter_churn_tokens_per_s.tokens_per_s")
        assert bench_diff.lower_is_better("swap_stall_s")
