"""Serving autoscaler: policies on synthetic traffic traces (the reference
tests its autoscaler with fake QPS traces), replica scale-out, gateway
round-robin."""

import time

import numpy as np

from fedml_tpu.serving.autoscale import (Autoscaler, ConcurrencyPolicy,
                                         EWMPolicy, Gateway, LookbackPolicy,
                                         ReplicaSet)


class TestPolicies:
    def test_ewm_tracks_qps_ramp(self):
        p = EWMPolicy(target_qps_per_replica=10.0, alpha=1.0)
        assert p.desired_replicas(5, 0.01, 1) == 1
        assert p.desired_replicas(25, 0.01, 1) == 3
        assert p.desired_replicas(95, 0.01, 3) == 10

    def test_ewm_smooths_spikes(self):
        p = EWMPolicy(target_qps_per_replica=10.0, alpha=0.2)
        p.desired_replicas(10, 0.01, 1)
        # a single 100-qps spike only nudges the EWM (0.2*100+0.8*10=28)
        assert p.desired_replicas(100, 0.01, 1) == 3

    def test_concurrency_littles_law(self):
        p = ConcurrencyPolicy(target_concurrency=4.0)
        # 100 qps x 0.2 s latency = 20 in flight -> 5 replicas
        assert p.desired_replicas(100, 0.2, 1) == 5
        assert p.desired_replicas(1, 0.01, 5) == 1

    def test_lookback_holds_burst_capacity(self):
        p = LookbackPolicy(target_qps_per_replica=10.0, window=5)
        trace = [5, 50, 5, 5, 5, 5]  # burst then quiet
        desired = [p.desired_replicas(q, 0.01, 1) for q in trace]
        assert desired[1] == 5           # scales on the burst
        assert desired[-1] == 5          # burst stays in the window
        # once the burst ages out of the window, capacity decays
        for _ in range(5):
            last = p.desired_replicas(5, 0.01, 5)
        assert last == 1


class _EchoPredictor:
    def predict(self, request):
        return {"echo": request.get("x", 0)}

    def ready(self):
        return True


def test_replicaset_gateway_and_autoscaler_end_to_end():
    rs = ReplicaSet(lambda: _EchoPredictor(), min_replicas=1,
                    max_replicas=4)
    gw = Gateway(rs, window_s=2.0)
    try:
        # round-robin across replicas, responses correct
        rs.scale_to(3)
        assert len(rs) == 3
        outs = [gw.predict({"x": i}) for i in range(6)]
        assert [o["echo"] for o in outs] == list(range(6))
        qps, lat = gw.metrics()
        assert qps > 0 and lat >= 0
        # autoscaler applies the policy verdict
        scaler = Autoscaler(gw, EWMPolicy(target_qps_per_replica=0.5,
                                          alpha=1.0))
        n = scaler.step()   # qps/0.5 with recent traffic -> scale up
        assert n >= 2
        # quiet window -> scale back toward min
        import time
        time.sleep(2.1)
        n = scaler.step()
        assert n == 1
    finally:
        rs.stop()


class _VersionedPredictor:
    def __init__(self, version):
        self.version = version

    def predict(self, request):
        return {"version": self.version}

    def ready(self):
        return True


class TestReplicaHealth:
    def test_dead_replica_is_replaced(self):
        rs = ReplicaSet(lambda: _EchoPredictor(), min_replicas=2,
                        max_replicas=4)
        gw = Gateway(rs)
        try:
            # simulate a crash: stop one replica's server out-of-band
            victim = rs.replicas[0]
            victim.stop()
            replaced = rs.health_check()
            assert replaced == 1
            assert len(rs) == 2
            # every replica answers again, including the replacement slot
            for _ in range(4):
                assert "echo" in gw.predict({"x": 1})
        finally:
            rs.stop()

    def test_autoscaler_step_heals(self):
        rs = ReplicaSet(lambda: _EchoPredictor(), min_replicas=2,
                        max_replicas=4)
        gw = Gateway(rs)
        scaler = Autoscaler(gw, EWMPolicy(target_qps_per_replica=1000.0))
        try:
            rs.replicas[1].stop()
            scaler.step()
            for _ in range(4):
                assert "echo" in gw.predict({"x": 2})
        finally:
            rs.stop()

    def test_rolling_update_zero_downtime(self):
        rs = ReplicaSet(lambda: _VersionedPredictor("v1"), min_replicas=3,
                        max_replicas=4)
        gw = Gateway(rs)
        try:
            import threading
            errors, versions = [], []

            def traffic():
                for _ in range(60):
                    try:
                        versions.append(gw.predict({})["version"])
                    except Exception as e:  # any failed request = downtime
                        errors.append(e)

            t = threading.Thread(target=traffic)
            t.start()
            rs.rolling_update(lambda: _VersionedPredictor("v2"))
            t.join()
            assert not errors, errors[:3]
            # rollout completed: fresh traffic is all v2
            assert all(gw.predict({})["version"] == "v2" for _ in range(3))
            assert "v1" in versions  # traffic overlapped the rollout
        finally:
            rs.stop()


class TestSubprocessReplicas:
    """Process-isolated replicas (VERDICT r3 item 6): each replica is a
    child OS process serving HTTP (the container analogue); SIGKILLing one
    never touches the gateway, and the health check replaces the corpse."""

    @staticmethod
    def _factory(tmp_path):
        import jax
        import numpy as np
        from types import SimpleNamespace
        from fedml_tpu.model import create
        from fedml_tpu.serving import save_model
        from fedml_tpu.serving.autoscale import subprocess_replica_factory

        args = SimpleNamespace(model="lr", dataset="digits")
        bundle = create(args, 10)
        params = bundle.init(jax.random.PRNGKey(0),
                             np.zeros((2, 64), np.float32))
        path = str(tmp_path / "model.fmtpu")
        save_model(jax.device_get(params), path)
        return subprocess_replica_factory(args, path, 10, str(tmp_path)), 64

    def test_kill9_survival_and_gateway_continuity(self, tmp_path):
        import os
        import signal
        import numpy as np

        factory, n_feat = self._factory(tmp_path)
        rs = ReplicaSet(replica_factory=factory, min_replicas=2,
                        max_replicas=4)
        try:
            # replicas are distinct OS processes, not this one
            pids = [r.pid for r in rs.replicas]
            assert len(set(pids)) == 2 and os.getpid() not in pids
            gw = Gateway(rs)
            req = {"inputs": np.zeros((2, n_feat)).tolist()}
            assert len(gw.predict(req)["classes"]) == 2

            # SIGKILL one replica: the hardest crash a container would die of
            os.kill(pids[0], signal.SIGKILL)
            deadline = time.time() + 10
            while time.time() < deadline and rs._probe(rs.replicas[0].port):
                time.sleep(0.1)
            replaced = rs.health_check()
            assert replaced == 1
            new_pids = [r.pid for r in rs.replicas]
            assert pids[0] not in new_pids and len(rs) == 2
            # gateway continuity: every post-kill request succeeds (round
            # robin crosses both the survivor and the replacement)
            for _ in range(4):
                assert len(gw.predict(req)["classes"]) == 2
        finally:
            rs.stop()
        # stop() reaps the children
        for r in rs.replicas:
            assert r.proc is None or r.proc.poll() is not None
