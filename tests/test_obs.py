"""Observability layer (core/obs): tracer spans/links/propagation, the
typed metrics registry + Prometheus exposition, the dispatch profiling
plane, JSONL schema validation (replaying a real engine run), the
mlops.event concurrency fix, sys_perf degradation, and the tracking
overhead regression gate."""

import io
import json
import os
import threading
import time

import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core import mlops, obs
from fedml_tpu.core.obs import flight as obs_flight
from fedml_tpu.core.obs import metrics as obs_metrics
from fedml_tpu.core.obs import profiler as obs_profiler
from fedml_tpu.core.obs import schema as obs_schema
from fedml_tpu.core.obs import trace as obs_trace

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _obs_defaults():
    """Every test starts from the documented defaults and leaves no sink
    attached (other test modules rely on tracking being inert)."""
    obs.configure(None)
    yield
    obs.configure(None)
    mlops.init(Arguments(enable_tracking=False))


def _init_sink(tmp_path, run_id, **overrides):
    args = Arguments(log_file_dir=str(tmp_path), run_id=run_id, **overrides)
    mlops.init(args)
    return os.path.join(str(tmp_path), f"run_{run_id}.jsonl")


def _read_records(path, kind=None):
    recs = [json.loads(l) for l in open(path) if l.strip()]
    return [r for r in recs if kind is None or r["kind"] == kind]


class TestTracer:
    def test_nesting_and_emission(self, tmp_path):
        path = _init_sink(tmp_path, "tr_nest")
        with obs_trace.span("outer", attrs={"k": 1}) as outer:
            assert obs_trace.current_span() is outer
            with obs_trace.span("inner") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_id == outer.span_id
        assert obs_trace.current_span() is None
        spans = _read_records(path, "span")
        assert [s["name"] for s in spans] == ["inner", "outer"]
        assert spans[0]["parent_id"] == spans[1]["span_id"]
        for s in spans:
            assert not obs_schema.validate_record(s), \
                obs_schema.validate_record(s)

    def test_root_forces_new_trace(self):
        with obs_trace.span("a") as a:
            with obs_trace.span("b", root=True) as b:
                assert b.trace_id != a.trace_id
                assert b.parent_id is None

    def test_traceparent_roundtrip(self):
        sp = obs_trace.tracer.start_span("x")
        ctx = obs_trace.parse_traceparent(sp.traceparent())
        assert ctx.trace_id == sp.trace_id
        assert ctx.span_id == sp.span_id
        sp.end()

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-zzzz-1234-01", 42,
        "00-" + "a" * 31 + "-" + "b" * 16 + "-01"])
    def test_malformed_traceparent_degrades_to_none(self, bad):
        assert obs_trace.parse_traceparent(bad) is None

    def test_message_inject_extract(self):
        from fedml_tpu.core.distributed.communication.message import Message
        msg = Message("t", 0, 1)
        with obs_trace.span("send") as sp:
            obs_trace.inject(msg)
        back = Message.decode(msg.encode())
        ctx = obs_trace.extract(back)
        assert ctx.span_id == sp.span_id
        assert ctx.trace_id == sp.trace_id

    def test_links_and_events(self, tmp_path):
        path = _init_sink(tmp_path, "tr_links")
        donor = obs_trace.tracer.start_span("upload")
        donor.end()
        with obs_trace.span("pour", root=True) as sp:
            sp.add_link(donor, staleness=3, client=7)
            sp.add_event("retry", attempt=1)
            # a link from a raw traceparent string too (the wire shape)
            sp.add_link(donor.traceparent(), staleness=0)
        pour = [s for s in _read_records(path, "span")
                if s["name"] == "pour"][0]
        assert len(pour["links"]) == 2
        assert pour["links"][0]["span_id"] == donor.span_id
        assert pour["links"][0]["attrs"]["staleness"] == 3
        assert pour["events"][0]["name"] == "retry"

    def test_disabled_tracing_is_inert(self, tmp_path):
        path = _init_sink(tmp_path, "tr_off", obs_tracing=False)
        with obs_trace.span("a") as sp:
            assert sp is obs_trace.NOOP_SPAN
            sp.add_event("x")
            sp.add_link(None)
            assert sp.traceparent() is None
        from fedml_tpu.core.distributed.communication.message import Message
        msg = Message("t", 0, 1)
        obs_trace.inject(msg)
        assert msg.get(Message.MSG_ARG_KEY_TRACEPARENT) is None
        assert not _read_records(path, "span")

    def test_noop_parent_does_not_mint_null_trace(self):
        """A _NoopSpan handle stored while tracing was off (the server
        managers' class-level defaults) must not become a parent with
        trace_id=None when tracing is on — that span record would
        violate the schema's HEX32 requirement."""
        sp = obs_trace.tracer.start_span("child",
                                         parent=obs_trace.NOOP_SPAN)
        try:
            assert sp.trace_id is not None and len(sp.trace_id) == 32
            assert sp.parent_id is None
        finally:
            sp.end()

    def test_end_is_idempotent(self):
        sp = obs_trace.tracer.start_span("once")
        d1 = sp.end()
        assert d1 is not None and sp.end() is None

    def test_mis_nested_exit_removes_right_span(self):
        a = obs_trace.tracer.start_span("a")
        b = obs_trace.tracer.start_span("b")
        a.__enter__()
        b.__enter__()
        a.__exit__(None, None, None)  # out of order
        assert obs_trace.current_span() is b
        b.__exit__(None, None, None)
        assert obs_trace.current_span() is None


class TestMetrics:
    def test_counter_gauge_histogram(self):
        reg = obs_metrics.MetricsRegistry()
        c = reg.counter("t_bytes", labels=("mt",))
        c.inc(10, mt="a")
        c.inc(5, mt="a")
        c.inc(1, mt="b")
        assert c.value(mt="a") == 15 and c.value(mt="b") == 1
        g = reg.gauge("t_mfu")
        g.set(0.4)
        assert g.value() == 0.4
        h = reg.histogram("t_stal", buckets=(1, 4, 16))
        for v in (0, 1, 3, 5, 100):
            h.observe(v)
        snap = h.snapshot()[0]
        assert snap["counts"] == [2, 1, 1, 1]  # <=1, <=4, <=16, +Inf
        assert snap["count"] == 5 and snap["sum"] == 109

    def test_counter_rejects_negative_and_type_conflicts(self):
        reg = obs_metrics.MetricsRegistry()
        c = reg.counter("t_c")
        with pytest.raises(ValueError):
            c.inc(-1)
        with pytest.raises(ValueError):
            reg.gauge("t_c")
        with pytest.raises(ValueError):
            reg.counter("t_c", labels=("x",))

    def test_exposition_format(self):
        reg = obs_metrics.MetricsRegistry()
        reg.counter("t_total", help="things", labels=("k",)).inc(3, k="v")
        reg.histogram("t_h", buckets=(1.0, 2.0)).observe(1.5)
        text = reg.exposition()
        assert "# HELP t_total things" in text
        assert "# TYPE t_total counter" in text
        assert 't_total{k="v"} 3.0' in text
        assert 't_h_bucket{le="1.0"} 0' in text
        assert 't_h_bucket{le="2.0"} 1' in text
        assert 't_h_bucket{le="+Inf"} 1' in text
        assert "t_h_sum 1.5" in text and "t_h_count 1" in text

    def test_snapshot_flush_record_validates(self, tmp_path):
        path = _init_sink(tmp_path, "m_flush")
        obs_metrics.REGISTRY.counter("t_flush_total").inc(2)
        obs_metrics.REGISTRY.flush(step=7)
        recs = _read_records(path, "metrics_snapshot")
        assert recs and recs[-1]["step"] == 7
        assert "t_flush_total" in recs[-1]["metrics"]
        assert not obs_schema.validate_record(recs[-1])

    def test_histogram_bucket_mismatch_raises(self):
        reg = obs_metrics.MetricsRegistry()
        h = reg.histogram("t_bk", buckets=(1.0, 2.0))
        # buckets=None on a re-get means "whatever is registered";
        # identical (even unsorted/int) bounds also re-get
        assert reg.histogram("t_bk") is h
        assert reg.histogram("t_bk", buckets=(2, 1)) is h
        # DIFFERENT bounds raise — observations would silently land in
        # buckets the caller never asked for
        with pytest.raises(ValueError):
            reg.histogram("t_bk", buckets=(1.0, 4.0))

    def test_wire_seam_feeds_registry(self):
        from fedml_tpu.core.distributed.communication.message import Message
        c = obs_metrics.REGISTRY.counter("fed_wire_bytes_total",
                                         labels=("msg_type",))
        before = c.value(msg_type="obs_wire_t")
        blob = Message("obs_wire_t", 0, 1).encode()
        assert c.value(msg_type="obs_wire_t") == before + len(blob)

    def test_maybe_flush_dedup_resets_per_run(self, tmp_path):
        """configure() (every mlops.init) resets the round-dedup: a
        second run in the same process must flush at its round 0 even
        though the first run also flushed at round 0."""
        path = _init_sink(tmp_path, "m_runs", obs_metrics_flush_rounds=5)
        obs_metrics.maybe_flush(0)
        obs_metrics.maybe_flush(0)  # same-round burst: deduped
        n1 = len(_read_records(path, "metrics_snapshot"))
        assert n1 == 1
        path2 = _init_sink(tmp_path, "m_runs2",
                           obs_metrics_flush_rounds=5)  # "new run"
        obs_metrics.maybe_flush(0)
        assert len(_read_records(path2, "metrics_snapshot")) == 1

    def test_engine_run_ends_with_final_snapshot(self, tmp_path):
        """The last cadence boundary is rarely the last round: run() must
        close with an unconditional snapshot or the tail rounds' metrics
        die with the process."""
        from fedml_tpu import data as data_mod
        from fedml_tpu import model as model_mod
        from fedml_tpu.core.algframe.client_trainer import (
            ClassificationTrainer)
        from fedml_tpu.optimizers.registry import create_optimizer
        from fedml_tpu.simulation.tpu.engine import TPUSimulator

        args = Arguments(dataset="synthetic_mnist", model="lr",
                         client_num_in_total=8, client_num_per_round=4,
                         comm_round=4, epochs=1, batch_size=16,
                         learning_rate=0.1, frequency_of_the_test=0,
                         random_seed=0, rounds_per_dispatch=2,
                         obs_metrics_flush_rounds=10,  # boundary: round 0
                         log_file_dir=str(tmp_path), run_id="m_final")
        mlops.init(args)
        fed, out_dim = data_mod.load(args)
        bundle = model_mod.create(args, out_dim)
        spec = ClassificationTrainer(bundle.apply)
        TPUSimulator(args, fed, bundle,
                     create_optimizer(args, spec), spec).run()
        snaps = _read_records(
            os.path.join(str(tmp_path), "run_m_final.jsonl"),
            "metrics_snapshot")
        assert snaps and snaps[-1]["step"] == 3  # final round, not 0
        assert "fed_dispatch_wall_seconds" in snaps[-1]["metrics"]

    def test_disabled_metrics_hooks_are_inert(self):
        obs_metrics.set_enabled(False)
        try:
            c = obs_metrics.REGISTRY.counter("fed_wire_bytes_total",
                                             labels=("msg_type",))
            before = c.value(msg_type="off_t")
            obs_metrics.record_wire("off_t", 123)
            assert c.value(msg_type="off_t") == before
        finally:
            obs_metrics.set_enabled(True)


class TestWallClockFlusher:
    def test_flushes_without_round_boundaries(self, tmp_path):
        """Serving / cross-device / agents never call log_round_info:
        the wall-clock cadence must snapshot their metrics anyway."""
        path = _init_sink(tmp_path, "wall_f", obs_metrics_flush_s=0.3)
        obs_metrics.REGISTRY.counter("t_wall_total").inc(3)
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if _read_records(path, "metrics_snapshot"):
                break
            time.sleep(0.05)
        snaps = _read_records(path, "metrics_snapshot")
        assert snaps, "no wall-clock metrics_snapshot within 5 s"
        assert "t_wall_total" in snaps[-1]["metrics"]
        assert not obs_schema.validate_record(snaps[-1])

    def test_idle_process_stays_silent(self, tmp_path):
        """No instrument change since the last snapshot → no re-emission
        (a fleet of idle replicas must not spam identical snapshots)."""
        path = _init_sink(tmp_path, "wall_idle", obs_metrics_flush_s=0.2)
        obs_metrics.REGISTRY.counter("t_idle_total").inc()
        deadline = time.time() + 5.0
        while time.time() < deadline and not _read_records(
                path, "metrics_snapshot"):
            time.sleep(0.05)
        n = len(_read_records(path, "metrics_snapshot"))
        assert n >= 1
        time.sleep(0.7)   # several cadences with zero activity
        assert len(_read_records(path, "metrics_snapshot")) == n

    def test_zero_disables(self, tmp_path):
        path = _init_sink(tmp_path, "wall_off", obs_metrics_flush_s=0)
        obs_metrics.REGISTRY.counter("t_off_total").inc()
        time.sleep(0.4)
        assert not _read_records(path, "metrics_snapshot")


class TestFlightRecorder:
    def test_ring_bounds_and_dump_validates(self, tmp_path):
        _init_sink(tmp_path, "fl_ring")
        rec = obs_flight.FlightRecorder("t_engine", capacity=8)
        for i in range(20):
            rec.note("step", tokens=i, occupancy=2)
        assert len(rec) == 8   # bounded: only the last moments survive
        path = rec.dump(str(tmp_path / "flight.jsonl"))
        lines = open(path).read().splitlines()
        assert len(lines) == 8
        problems = obs_schema.validate_lines(lines)
        assert not problems, problems
        recs = [json.loads(l) for l in lines]
        assert [r["seq"] for r in recs] == sorted(r["seq"] for r in recs)
        assert recs[-1]["data"]["tokens"] == 19  # newest kept
        assert all(r["component"] == "t_engine" for r in recs)

    def test_empty_ring_dumps_nothing(self, tmp_path):
        rec = obs_flight.FlightRecorder("t_empty")
        assert rec.dump(str(tmp_path / "nope.jsonl")) is None
        assert not os.path.exists(tmp_path / "nope.jsonl")

    def test_log_health_record_validates(self, tmp_path):
        path = _init_sink(tmp_path, "fl_health")
        mlops.log_health("serving_engine", "stalled",
                         detail={"occupancy": 3})
        rec = _read_records(path, "health")[-1]
        assert not obs_schema.validate_record(rec)
        assert rec["component"] == "serving_engine"
        assert rec["status"] == "stalled"


class TestWatchdog:
    def _state(self, **kw):
        base = {"occupancy": 2, "last_progress_ts": time.time(),
                "poisoned": False}
        base.update(kw)
        return base

    def test_stall_trip_dump_and_rearm(self, tmp_path):
        path = _init_sink(tmp_path, "wd_stall")
        rec = obs_flight.FlightRecorder("t_wd", capacity=4)
        rec.note("step", tokens=1)
        state = self._state(last_progress_ts=time.time() - 99)
        dump = str(tmp_path / "wd_flight.jsonl")
        wd = obs_flight.Watchdog("t_wd", lambda: state, recorder=rec,
                                 stall_s=1.0, dump_path=dump)
        assert wd.check() == "stalled"
        assert wd.trips == 1
        # same episode: no re-trip, no second dump spam
        assert wd.check() is None
        # the black box landed and validates line by line
        assert not obs_schema.validate_lines(
            open(dump).read().splitlines())
        # progress resumes -> re-arms -> a NEW stall trips again
        state["last_progress_ts"] = time.time()
        assert wd.check() is None
        state["last_progress_ts"] = time.time() - 99
        assert wd.check() == "stalled"
        assert wd.trips == 2
        # trips flowed to the registry and the health record stream
        c = obs_metrics.REGISTRY.counter("obs_watchdog_trips_total",
                                         labels=("component", "reason"))
        assert c.value(component="t_wd", reason="stalled") >= 2
        healths = _read_records(path, "health")
        assert healths and healths[-1]["status"] == "stalled"
        assert not obs_schema.validate_record(healths[-1])

    def test_nan_trips_even_with_progress(self):
        state = self._state(poisoned=True)
        wd = obs_flight.Watchdog("t_nan", lambda: state, stall_s=1.0)
        assert wd.check() == "nan_logits"

    def test_idle_engine_never_trips(self):
        state = self._state(occupancy=0,
                            last_progress_ts=time.time() - 999)
        wd = obs_flight.Watchdog("t_idle", lambda: state, stall_s=1.0)
        assert wd.check() is None

    def test_probe_failure_is_survivable(self):
        def boom():
            raise RuntimeError("probe exploded")
        wd = obs_flight.Watchdog("t_boom", boom, stall_s=1.0)
        assert wd.check() is None  # no trip, no raise


class TestProfiler:
    def test_peak_table_and_mfu_math(self):
        class Dev:
            device_kind = "cpu"
        assert obs_profiler.peak_tflops(Dev()) == 0.5

        class Unknown:
            device_kind = "quantum9000"
        assert obs_profiler.peak_tflops(Unknown()) is None
        # 1e12 FLOPs in 1 s over 2 chips of 0.5 TFLOP/s peak = 100% MFU
        assert obs_profiler.mfu_value(1e12, 1.0, 2,
                                      peak_tflops_per_chip=0.5) == \
            pytest.approx(1.0)
        assert obs_profiler.mfu_value(0.0, 1.0, 2,
                                      peak_tflops_per_chip=0.5) is None

    def test_dispatch_profile_record_and_gauge(self, tmp_path):
        path = _init_sink(tmp_path, "prof")
        mfu = obs_profiler.record_dispatch_profile(
            "round", rounds=2, host_s=0.01, device_wait_s=0.99,
            flops_per_round=0.5e12, n_devices=2)
        # 1e12 FLOPs over 1.0 s on 2 cpu-peak chips -> MFU 1.0
        assert mfu == pytest.approx(1.0, rel=0.05)
        rec = _read_records(path, "profile")[-1]
        assert not obs_schema.validate_record(rec)
        assert rec["dispatch"] == "round" and rec["rounds"] == 2
        assert rec["device_wait_s"] == pytest.approx(0.99)
        g = obs_metrics.REGISTRY.gauge("fed_round_mfu")
        assert g.value() == pytest.approx(mfu, rel=1e-6)

    def test_non_training_dispatch_gets_no_mfu(self, tmp_path):
        """Host-robust path: the server_update dispatch is a millisecond
        aggregation — crediting it a full round's FLOPs produced a >1.0
        MFU that overwrote the real per-round gauge every round."""
        from fedml_tpu import data as data_mod
        from fedml_tpu import model as model_mod
        from fedml_tpu.core.algframe.client_trainer import (
            ClassificationTrainer)
        from fedml_tpu.optimizers.registry import create_optimizer
        from fedml_tpu.simulation.tpu.engine import TPUSimulator

        args = Arguments(dataset="synthetic_mnist", model="lr",
                         client_num_in_total=8, client_num_per_round=4,
                         comm_round=2, epochs=1, batch_size=16,
                         learning_rate=0.1, frequency_of_the_test=0,
                         random_seed=0, obs_profile_device=True,
                         enable_defense=True, defense_type="krum",
                         byzantine_client_num=1, robust_fused="host",
                         log_file_dir=str(tmp_path), run_id="prof_host")
        mlops.init(args)
        path = os.path.join(str(tmp_path), "run_prof_host.jsonl")
        fed, out_dim = data_mod.load(args)
        bundle = model_mod.create(args, out_dim)
        spec = ClassificationTrainer(bundle.apply)
        sim = TPUSimulator(args, fed, bundle,
                           create_optimizer(args, spec), spec)
        assert not sim.robust_fused  # host path: separate server_update
        sim.run()
        profs = _read_records(path, "profile")
        by_name = {}
        for p in profs:
            by_name.setdefault(p["dispatch"], []).append(p)
        assert "server_update" in by_name and "robust_collect" in by_name
        assert all("mfu" not in p for p in by_name["server_update"])
        assert any("mfu" in p for p in by_name["robust_collect"])
        for p in by_name["robust_collect"]:
            if "mfu" in p:
                assert 0.0 < p["mfu"] <= 1.0

    def test_engine_device_profiling_emits_mfu(self, tmp_path):
        """Opt-in plane end-to-end: a tiny engine run with
        obs_profile_device emits profile records whose MFU comes from
        the same FLOPs model the bench uses."""
        from fedml_tpu import data as data_mod
        from fedml_tpu import model as model_mod
        from fedml_tpu.core.algframe.client_trainer import (
            ClassificationTrainer)
        from fedml_tpu.optimizers.registry import create_optimizer
        from fedml_tpu.simulation.tpu.engine import TPUSimulator

        args = Arguments(dataset="synthetic_mnist", model="lr",
                         client_num_in_total=8, client_num_per_round=4,
                         comm_round=2, epochs=1, batch_size=16,
                         learning_rate=0.1, frequency_of_the_test=0,
                         random_seed=0, rounds_per_dispatch=2,
                         obs_profile_device=True,
                         log_file_dir=str(tmp_path), run_id="prof_e2e")
        path = _init_sink(tmp_path, "prof_e2e", obs_profile_device=True)
        fed, out_dim = data_mod.load(args)
        bundle = model_mod.create(args, out_dim)
        spec = ClassificationTrainer(bundle.apply)
        sim = TPUSimulator(args, fed, bundle,
                           create_optimizer(args, spec), spec)
        sim.run()
        profs = _read_records(path, "profile")
        assert profs, "no profile records with obs_profile_device on"
        assert all("device_wait_s" in p for p in profs)
        assert any(p.get("mfu") is not None for p in profs)


class TestSchemaReplay:
    def test_engine_run_log_validates_line_by_line(self, tmp_path):
        """The tier-1 replay gate: run a small engine session with
        tracking on and validate EVERY line of the run log against the
        canonical schema table."""
        from fedml_tpu import data as data_mod
        from fedml_tpu import model as model_mod
        from fedml_tpu.core.algframe.client_trainer import (
            ClassificationTrainer)
        from fedml_tpu.optimizers.registry import create_optimizer
        from fedml_tpu.simulation.tpu.engine import TPUSimulator

        args = Arguments(dataset="synthetic_mnist", model="lr",
                         client_num_in_total=8, client_num_per_round=4,
                         comm_round=4, epochs=1, batch_size=16,
                         learning_rate=0.1, frequency_of_the_test=2,
                         random_seed=0, rounds_per_dispatch=2,
                         log_file_dir=str(tmp_path), run_id="replay",
                         obs_metrics_flush_rounds=2)
        mlops.init(args)
        path = os.path.join(str(tmp_path), "run_replay.jsonl")
        fed, out_dim = data_mod.load(args)
        bundle = model_mod.create(args, out_dim)
        spec = ClassificationTrainer(bundle.apply)
        sim = TPUSimulator(args, fed, bundle,
                           create_optimizer(args, spec), spec)
        sim.run()
        # a sample of every hand-built record kind rides along, so the
        # replay covers the full table, not just what this run emits
        mlops.log_comm_round(0, 1234, compression=None)
        mlops.log_chaos(round_idx=0, injected={"dropped": [1]})
        mlops.log_selection(0, "uniform", sampled=[0, 1], excluded=[],
                            target_n=2)
        mlops.log_training_status("RUNNING")
        mlops.log_model_info(0, "/tmp/x")
        mlops.log_health("serving_engine", "ok", detail={"occupancy": 0})
        mlops.log({"acc": 0.5}, step=0)
        with mlops.event("probe", round_idx=0):
            pass
        mlops._emit("sys_perf", mlops._sys_sample())
        lines = open(path).read().splitlines()
        problems = obs_schema.validate_lines(lines)
        assert not problems, problems[:20]
        kinds = {json.loads(l)["kind"] for l in lines}
        # the three planes all landed in one self-contained log
        assert {"span", "dispatch", "round", "metric",
                "metrics_snapshot"} <= kinds

    def test_unknown_kind_and_bad_types_are_flagged(self):
        assert obs_schema.validate_record({"kind": "nope", "ts": 1.0,
                                           "run_id": "0"})
        errs = obs_schema.validate_record(
            {"kind": "dispatch", "ts": 1.0, "run_id": "0",
             "dispatch": "r", "wall_s": "fast", "rounds": 1,
             "compiles": 0})
        assert any("wall_s" in e for e in errs)
        errs = obs_schema.validate_record(
            {"kind": "span", "ts": 1.0, "run_id": "0", "name": "x",
             "trace_id": "not-hex", "span_id": "b" * 16,
             "parent_id": None, "start_ts": 1.0, "end_ts": 2.0,
             "duration_s": 1.0, "pid": 1})
        assert any("trace_id" in e for e in errs)


class TestEventShim:
    def test_concurrent_same_name_spans_do_not_clobber(self, tmp_path):
        """The satellite fix: two threads bracketing a same-name event
        used to share one class-level start time — the first end stole
        the second start and one duration came out garbage."""
        path = _init_sink(tmp_path, "ev_conc")
        durs = {"fast": 0.05, "slow": 0.25}

        def worker(dur):
            mlops.event("train", started=True)
            time.sleep(dur)
            mlops.event("train", started=False, which=dur)

        ts = [threading.Thread(target=worker, args=(d,))
              for d in durs.values()]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        ends = _read_records(path, "event_end")
        assert len(ends) == 2
        by_which = {e["which"]: e["duration_s"] for e in ends}
        for d in durs.values():
            assert by_which[d] == pytest.approx(d, abs=0.04), by_which
        # the tracer half: two distinct train spans, not one
        spans = [s for s in _read_records(path, "span")
                 if s["name"] == "train"]
        assert len(spans) == 2
        assert spans[0]["span_id"] != spans[1]["span_id"]

    def test_context_manager_form_emits_span_and_legacy_pair(
            self, tmp_path):
        path = _init_sink(tmp_path, "ev_cm")
        with mlops.event("train", round_idx=3):
            time.sleep(0.01)
        assert _read_records(path, "event_start")
        end = _read_records(path, "event_end")[-1]
        assert end["duration_s"] >= 0.01
        sp = [s for s in _read_records(path, "span")
              if s["name"] == "train"][-1]
        assert sp["attrs"]["round_idx"] == 3

    def test_pair_api_duration_survives_tracing_off(self, tmp_path):
        path = _init_sink(tmp_path, "ev_off", obs_tracing=False)
        mlops.event("agg", started=True)
        time.sleep(0.02)
        mlops.event("agg", started=False)
        end = _read_records(path, "event_end")[-1]
        assert end["duration_s"] == pytest.approx(0.02, abs=0.03)

    def test_unmatched_end_is_harmless(self, tmp_path):
        path = _init_sink(tmp_path, "ev_un")
        mlops.event("never_started", started=False)
        end = _read_records(path, "event_end")[-1]
        assert end["duration_s"] is None


class TestSysPerf:
    def test_absent_psutil_degrades_once_to_jax_only(self, monkeypatch,
                                                     caplog):
        import sys as _sys
        monkeypatch.setitem(_sys.modules, "psutil", None)
        monkeypatch.setitem(mlops._sys_perf_state, "psutil_warned", False)
        import logging
        with caplog.at_level(logging.WARNING,
                             logger="fedml_tpu.core.mlops"):
            rec1 = mlops._sys_sample()  # must not raise
            rec2 = mlops._sys_sample()
        assert rec1.get("degraded") is True
        assert "cpu_pct" not in rec1
        warns = [r for r in caplog.records if "psutil" in r.getMessage()]
        assert len(warns) == 1, "degradation must be loud exactly ONCE"
        assert not obs_schema.validate_record(
            {**rec2, "kind": "sys_perf", "ts": 1.0, "run_id": "0"})

    def test_sampler_thread_survives_sample_failure(self, monkeypatch):
        monkeypatch.setitem(mlops._sys_perf_state, "sample_warned", False)
        calls = []

        def boom():
            calls.append(1)
            raise RuntimeError("sample exploded")

        monkeypatch.setattr(mlops, "_sys_sample", boom)
        mlops.stop_sys_perf()
        mlops.start_sys_perf(interval_s=0.01)
        time.sleep(0.08)
        mlops.stop_sys_perf()
        assert len(calls) >= 2, "sampler thread died on first failure"


class TestOverhead:
    def test_tracking_overhead_within_two_percent(self, tmp_path):
        """The CI gate the ISSUE pins: tracking-on vs tracking-off
        dispatch wall time within 2% on the 8-round digits block. One
        simulator serves both modes (the obs hooks consult process
        config at call time), trials alternate modes to cancel drift,
        and min-of-N is compared with a 4 ms timer-noise floor."""
        import jax.numpy as jnp

        from fedml_tpu import data as data_mod
        from fedml_tpu import model as model_mod
        from fedml_tpu.core.algframe.client_trainer import (
            ClassificationTrainer)
        from fedml_tpu.core.algframe.types import TrainHyper
        from fedml_tpu.optimizers.registry import create_optimizer
        from fedml_tpu.simulation.tpu.engine import TPUSimulator

        args = Arguments(dataset="digits", model="lr",
                         client_num_in_total=10, client_num_per_round=10,
                         comm_round=10_000, epochs=1, batch_size=32,
                         learning_rate=0.1, frequency_of_the_test=0,
                         random_seed=0, rounds_per_dispatch=8)
        fed, out_dim = data_mod.load(args)
        bundle = model_mod.create(args, out_dim)
        spec = ClassificationTrainer(bundle.apply)
        sim = TPUSimulator(args, fed, bundle,
                           create_optimizer(args, spec), spec)
        hyper = TrainHyper(learning_rate=jnp.float32(0.1), epochs=1)
        on_args = Arguments(log_file_dir=str(tmp_path), run_id="ovh")
        off_args = Arguments(enable_tracking=False, obs_tracing=False,
                             obs_metrics=False)
        r = [0]

        def block():
            import jax
            out = sim.run_rounds_fused(r[0], 8, hyper)
            jax.block_until_ready(sim.params)
            r[0] += 8
            return out

        # warmup both modes (compile + first-span costs)
        mlops.init(on_args)
        block()
        mlops.init(off_args)
        block()
        on_t, off_t = [], []
        for _ in range(8):   # min-of-8: this box's scheduler noise spans
            # 2-3x on a bad minute; more interleaved pairs beat a wider
            # tolerance (the 2% bound is the acceptance criterion)
            mlops.init(off_args)
            t0 = time.perf_counter()
            block()
            off_t.append(time.perf_counter() - t0)
            mlops.init(on_args)
            t0 = time.perf_counter()
            block()
            on_t.append(time.perf_counter() - t0)
        mlops.init(Arguments(enable_tracking=False))
        best_on, best_off = min(on_t), min(off_t)
        assert best_on <= best_off * 1.02 + 0.004, (
            f"tracking-on dispatch {best_on:.4f}s vs off {best_off:.4f}s "
            f"(> 2% + 4ms): on={on_t} off={off_t}")


class TestBenchDiff:
    def _mod(self):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "scripts"))
        import bench_diff
        return bench_diff

    def _write(self, tmp_path, name, lines):
        p = tmp_path / name
        p.write_text("\n".join(json.dumps(l) for l in lines) + "\n")
        return str(p)

    def test_direction_inference_and_gate(self, tmp_path):
        bd = self._mod()
        old = self._write(tmp_path, "old.jsonl", [
            {"metric": "x_rounds_per_hour", "value": 100.0},
            {"metric": "x_time_to_90pct_s", "value": 10.0},
            {"metric": "llm_serving_tokens_per_s", "value": 500.0,
             "legs": {"batched_c8": {"tokens_per_s": 400.0,
                                     "p99_latency_s": 0.5}}}])
        # throughput up + latency down = all improvements -> rc 0
        good = self._write(tmp_path, "good.jsonl", [
            {"metric": "x_rounds_per_hour", "value": 150.0},
            {"metric": "x_time_to_90pct_s", "value": 8.0},
            {"metric": "llm_serving_tokens_per_s", "value": 600.0,
             "legs": {"batched_c8": {"tokens_per_s": 480.0,
                                     "p99_latency_s": 0.4}}}])
        io_ = io.StringIO()
        assert bd.diff(bd.flatten(old), bd.flatten(good), 0.10,
                       out=io_) == 0
        # throughput DOWN past threshold -> rc 1, named in the summary
        bad = self._write(tmp_path, "bad.jsonl", [
            {"metric": "x_rounds_per_hour", "value": 50.0},
            {"metric": "x_time_to_90pct_s", "value": 10.0}])
        io_ = io.StringIO()
        assert bd.diff(bd.flatten(old), bd.flatten(bad), 0.10,
                       out=io_) == 1
        assert "x_rounds_per_hour" in io_.getvalue()
        assert "REGRESSED" in io_.getvalue()

    def test_reads_bench_wrapper_tail(self, tmp_path):
        bd = self._mod()
        wrapper = tmp_path / "BENCH_x.json"
        wrapper.write_text(json.dumps({
            "rc": 0, "tail": 'noise\n'
            + json.dumps({"metric": "m_rounds_per_hour",
                          "value": 7.0}) + "\n"}))
        assert bd.flatten(str(wrapper)) == {"m_rounds_per_hour": 7.0}

    def test_disjoint_files_exit_2(self, tmp_path):
        bd = self._mod()
        a = self._write(tmp_path, "a.jsonl", [{"metric": "a", "value": 1}])
        b = self._write(tmp_path, "b.jsonl", [{"metric": "b", "value": 1}])
        assert bd.diff(bd.flatten(a), bd.flatten(b), 0.1,
                       out=io.StringIO()) == 2


class TestTraceReport:
    def _mk_span(self, name, trace_id, span_id, parent, t0, t1, **attrs):
        rec = {"kind": "span", "ts": t1, "run_id": "0", "name": name,
               "trace_id": trace_id, "span_id": span_id,
               "parent_id": parent, "start_ts": t0, "end_ts": t1,
               "duration_s": t1 - t0, "pid": 1}
        if attrs:
            rec["attrs"] = attrs
        return rec

    def _round_spans(self, gap=0.001):
        tid, rid = "a" * 32, "1" * 16
        spans = [self._mk_span("round", tid, rid, None, 0.0, 10.0,
                               round_idx=0)]
        spans.append(self._mk_span("broadcast", tid, "2" * 16, rid,
                                   0.0, 1.0))
        spans.append(self._mk_span("wait.uploads", tid, "3" * 16, rid,
                                   1.0 + gap, 8.0))
        spans.append(self._mk_span("train", tid, "4" * 16, "2" * 16,
                                   1.5, 7.0))  # overlaps wait: no dbl count
        spans.append(self._mk_span("aggregate", tid, "5" * 16, rid,
                                   8.0 + gap, 9.0))
        spans.append(self._mk_span("eval", tid, "6" * 16, rid,
                                   9.0 + gap, 10.0))
        return spans

    def test_attribution_and_categories(self, tmp_path):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "scripts"))
        import trace_report
        out = io.StringIO()
        rc = trace_report.print_report(self._round_spans(), None,
                                       min_attr=0.95, out=out)
        text = out.getvalue()
        assert rc == 0, text
        assert "round[round_idx=0]" in text
        # the wait column is the 1.0→8.0 straggler window (~7 s); train
        # overlaps it but the union-based attribution never double-counts
        assert "6.999" in text and "attribution mean" in text

    def test_eval_checkpoint_roots_reported(self):
        """The engine's post-block per-round eval/checkpoint spans are
        ROOTS (root=True, outside the fused block span) — the report must
        show them, not drop them as unknown root names."""
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "scripts"))
        import trace_report
        spans = self._round_spans()
        spans.append(self._mk_span("eval", "e" * 32, "a1" * 8, None,
                                   10.0, 10.5, round_idx=0))
        spans.append(self._mk_span("checkpoint", "f" * 32, "b1" * 8, None,
                                   10.5, 10.6, round_idx=0))
        out = io.StringIO()
        rc = trace_report.print_report(spans, None, min_attr=0.0, out=out)
        text = out.getvalue()
        assert rc == 0, text
        assert "eval[round_idx=0]" in text
        assert "checkpoint[round_idx=0]" in text

    def test_low_attribution_fails_gate(self):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "scripts"))
        import trace_report
        tid, rid = "b" * 32, "7" * 16
        spans = [self._mk_span("round", tid, rid, None, 0.0, 10.0),
                 self._mk_span("broadcast", tid, "8" * 16, rid, 0.0, 1.0)]
        out = io.StringIO()
        rc = trace_report.print_report(spans, None, min_attr=0.95, out=out)
        assert rc == 2
        assert "FAIL" in out.getvalue()

    def test_orphan_subtree_reported_not_dropped(self):
        """A silo log passed without the server's: the silo.round spans
        reference a parent the report never saw — they must surface as
        orphan roots, not vanish."""
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "scripts"))
        import trace_report
        tid = "c" * 32
        spans = [self._mk_span("silo.round", tid, "9" * 16,
                               "dead" * 4, 0.0, 1.0),
                 self._mk_span("train", tid, "e" * 16, "9" * 16,
                               0.05, 0.95)]
        out = io.StringIO()
        rc = trace_report.print_report(spans, None, min_attr=0.0, out=out)
        text = out.getvalue()
        assert rc == 0, text
        assert "silo.round" in text
        # a genuinely-parentless stray (comm.send outside any session)
        # still stays out of the round report
        stray = [self._mk_span("comm.send", "d" * 32, "f" * 16,
                               None, 0.0, 0.1)]
        out = io.StringIO()
        rc = trace_report.print_report(stray, None, min_attr=0.0, out=out)
        assert rc == 1 and "no round/pour/block" in out.getvalue()

    def test_cli_end_to_end(self, tmp_path):
        import subprocess
        import sys
        path = tmp_path / "run.jsonl"
        with open(path, "w") as f:
            for s in self._round_spans():
                f.write(json.dumps(s) + "\n")
        script = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "trace_report.py")
        proc = subprocess.run([sys.executable, script, str(path),
                               "--min-attr", "0.95"],
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "attribution mean" in proc.stdout
