"""The minimum end-to-end slice (SURVEY §7): FedAvg on MNIST-shaped data with
LR, SP golden loop vs TPU mesh backend — learning happens and the two
backends agree."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments


def make_args(**kw):
    base = dict(
        dataset="synthetic_mnist", model="lr",
        client_num_in_total=8, client_num_per_round=8,
        comm_round=4, epochs=1, batch_size=16, learning_rate=0.1,
        frequency_of_the_test=2, random_seed=42,
    )
    base.update(kw)
    return Arguments(**base)


def test_devices_virtualized():
    assert jax.device_count() == 8


def test_sp_golden_loop_learns():
    result = fedml_tpu.run_simulation(backend="sp", args=make_args(comm_round=10))
    assert result["final_test_acc"] > 0.5, result["history"][-1]


def test_tpu_mesh_backend_learns():
    result = fedml_tpu.run_simulation(backend="tpu", args=make_args(comm_round=10))
    assert result["final_test_acc"] > 0.5, result["history"][-1]


def test_sp_tpu_parity():
    """The reference's strongest testability idea made first-class: the mesh
    backend must match the golden single-process loop numerically."""
    r_sp = fedml_tpu.run_simulation(backend="sp", args=make_args())
    r_tpu = fedml_tpu.run_simulation(backend="tpu", args=make_args())
    flat_sp = jax.tree_util.tree_leaves(r_sp["params"])
    flat_tpu = jax.tree_util.tree_leaves(r_tpu["params"])
    for a, b in zip(flat_sp, flat_tpu):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_partial_participation_parity():
    """Sampling fewer clients than total exercises the schedule tensor path."""
    kw = dict(client_num_in_total=16, client_num_per_round=5, comm_round=3)
    r_sp = fedml_tpu.run_simulation(backend="sp", args=make_args(**kw))
    r_tpu = fedml_tpu.run_simulation(backend="tpu", args=make_args(**kw))
    for a, b in zip(jax.tree_util.tree_leaves(r_sp["params"]),
                    jax.tree_util.tree_leaves(r_tpu["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_uneven_clients_over_devices():
    """client_num_in_total not divisible by device count → dummy padding."""
    result = fedml_tpu.run_simulation(
        backend="tpu", args=make_args(client_num_in_total=11,
                                      client_num_per_round=6, comm_round=2))
    assert np.isfinite(result["final_test_acc"])
