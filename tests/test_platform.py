"""Platform layer: local-first api/, cli/, workflow/, serving/.

Done-criterion from the build plan: the CLI runs a simulation from a YAML
and the resulting model is served over HTTP (reference ``cli/cli.py:11-77``,
``api/__init__.py:29-43``, ``workflow/workflow.py:42``,
``serving/fedml_predictor.py:4``)."""

import json
import os
import sys
import time
import urllib.request

import numpy as np
import pytest

from fedml_tpu import api
from fedml_tpu.arguments import Arguments


@pytest.fixture()
def runs_dir(tmp_path, monkeypatch):
    d = tmp_path / "runs"
    monkeypatch.setenv("FEDML_TPU_RUNS_DIR", str(d))
    return d


def _wait_status(run_id, want, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        status = api.run_status(run_id)
        if status in want:
            return status
        time.sleep(0.3)
    return api.run_status(run_id)


class TestApi:
    def test_task_job_lifecycle(self, runs_dir, tmp_path):
        job = tmp_path / "job.yaml"
        job.write_text("workspace: .\njob: echo hello-from-job; exit 0\n")
        assert api.fedml_login("k") == 0
        res = api.launch_job(str(job))
        assert res.result_code == 0 and res.run_id
        status = _wait_status(res.run_id, {api.STATUS_FINISHED,
                                           api.STATUS_FAILED})
        assert status == api.STATUS_FINISHED
        assert any("hello-from-job" in l for l in api.run_logs(res.run_id))
        # stopping a finished run must NOT clobber its record
        assert api.run_stop(res.run_id)
        assert api.run_status(res.run_id) == api.STATUS_FINISHED
        assert any(m["run_id"] == res.run_id for m in api.run_list())

    def test_failed_job_status(self, runs_dir, tmp_path):
        job = tmp_path / "job.yaml"
        job.write_text("job: exit 3\n")
        res = api.launch_job(str(job), detach=False)
        assert res.result_code == -1
        assert api.run_status(res.run_id) == api.STATUS_FAILED

    def test_stop_running_job(self, runs_dir, tmp_path):
        job = tmp_path / "job.yaml"
        job.write_text("job: sleep 60\n")
        res = api.launch_job(str(job))
        assert api.run_status(res.run_id) == api.STATUS_RUNNING
        assert api.run_stop(res.run_id)
        assert api.run_status(res.run_id) == api.STATUS_KILLED

    def test_build_packages_workspace(self, tmp_path):
        src = tmp_path / "ws"
        src.mkdir()
        (src / "main.py").write_text("print('hi')\n")
        cfg = tmp_path / "conf.yaml"
        cfg.write_text("a: 1\n")
        dest = api.build(str(src), str(tmp_path / "out.zip"), str(cfg))
        import zipfile
        names = zipfile.ZipFile(dest).namelist()
        assert "main.py" in names
        assert "conf/conf.yaml" in names


class TestCliTrainAndServe:
    def test_cli_runs_sim_from_yaml_and_model_serves(self, runs_dir,
                                                     tmp_path):
        """The full platform slice: yaml -> CLI train subprocess ->
        checkpointed params -> HTTP serving."""
        ckpt = tmp_path / "model.pkl"
        cfg = tmp_path / "fedml_config.yaml"
        cfg.write_text(f"""
common_args:
  training_type: simulation
  random_seed: 0
data_args:
  dataset: synthetic_mnist
train_args:
  client_num_in_total: 4
  client_num_per_round: 4
  comm_round: 2
  epochs: 1
  batch_size: 16
  learning_rate: 0.1
model_args:
  model: lr
tracking_args:
  save_model_path: {ckpt}
""")
        res = api.launch_job(str(cfg), detach=False)
        logs = "\n".join(api.run_logs(res.run_id))
        assert res.result_code == 0, logs
        assert api.run_status(res.run_id) == api.STATUS_FINISHED
        assert ckpt.exists(), logs

        runner = api.model_serve(str(ckpt), model="lr", output_dim=10)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{runner.port}/ready") as r:
                assert json.load(r)["ready"] is True
            x = np.zeros((2, 784), np.float32).tolist()
            req = urllib.request.Request(
                f"http://127.0.0.1:{runner.port}/predict",
                data=json.dumps({"inputs": x}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                out = json.load(r)
            assert len(out["classes"]) == 2
            assert len(out["outputs"][0]) == 10
        finally:
            runner.stop()

    def test_cli_version_env(self):
        from click.testing import CliRunner

        from fedml_tpu.cli.main import cli
        r = CliRunner().invoke(cli, ["version"])
        assert r.exit_code == 0 and "fedml_tpu version" in r.output
        r = CliRunner().invoke(cli, ["env"])
        assert r.exit_code == 0 and "jax backend" in r.output


class TestWorkflow:
    def test_dag_order_and_outputs(self):
        from fedml_tpu.workflow import CallableJob, Workflow
        wf = Workflow("t", max_workers=2)
        a = wf.add_job(CallableJob("a", lambda: 1))
        b = wf.add_job(CallableJob("b", lambda inp: inp["a"] + 1), [a])
        c = wf.add_job(CallableJob("c", lambda inp: inp["b"] * 10), [b])
        out = wf.run()
        assert out == {"a": 1, "b": 2, "c": 20}

    def test_failure_cancels_dependents(self):
        from fedml_tpu.workflow import CallableJob, JobStatus, Workflow
        wf = Workflow("t")

        def boom():
            raise RuntimeError("boom")

        a = wf.add_job(CallableJob("a", boom))
        b = wf.add_job(CallableJob("b", lambda inp: 1), [a])
        with pytest.raises(RuntimeError, match="1 job"):
            wf.run()
        assert wf.jobs["a"].status == JobStatus.FAILED
        assert wf.jobs["b"].status == JobStatus.CANCELLED

    def test_cycle_detection(self):
        from fedml_tpu.workflow import CallableJob, Workflow
        wf = Workflow("t")
        a = wf.add_job(CallableJob("a", lambda: 1))
        b = wf.add_job(CallableJob("b", lambda: 2), [a])
        a.dependencies = [b]  # force a cycle
        with pytest.raises(ValueError, match="cyclic"):
            wf.run()

    def test_launch_job_in_workflow(self, runs_dir, tmp_path):
        from fedml_tpu.workflow import CallableJob, LaunchJob, Workflow
        job = tmp_path / "job.yaml"
        job.write_text("job: echo wf-step-done\n")
        wf = Workflow("launcher")
        a = wf.add_job(LaunchJob("train", str(job)))
        b = wf.add_job(
            CallableJob("check",
                        lambda inp: any("wf-step-done" in l
                                        for l in inp["train"]["logs"])),
            [a])
        out = wf.run()
        assert out["check"] is True


class TestDiagnosis:
    def test_diagnosis_all_ok(self):
        from fedml_tpu.utils.diagnosis import run_diagnosis
        report = run_diagnosis()
        assert set(report) == {"device", "grpc", "tcp"}
        for name, (ok, detail) in report.items():
            assert ok, f"{name}: {detail}"


def test_run_wait_timeout_kills(runs_dir, tmp_path):
    """Job-monitor: a hung job is stopped when the wait deadline passes."""
    job = tmp_path / "job.yaml"
    job.write_text("job: sleep 120\n")
    res = api.launch_job(str(job))
    status = api.run_wait(res.run_id, timeout_s=2.0)
    assert status == api.STATUS_KILLED


def test_run_wait_returns_finished(runs_dir, tmp_path):
    job = tmp_path / "job.yaml"
    job.write_text("job: echo done\n")
    res = api.launch_job(str(job))
    assert api.run_wait(res.run_id, timeout_s=30.0) == api.STATUS_FINISHED
