"""Resource scheduler + job monitor (VERDICT r4 item 7): sqlite
allocation store with a matcher consulted by launch_job, and a periodic
monitor that detects SIGKILLed runs, releases their capacity, and
restarts opted-in jobs."""

import os
import signal
import textwrap
import time

import pytest

from fedml_tpu import api
from fedml_tpu.api.scheduler import JobMonitor, ResourceDB, _reset_default_db

pytestmark = pytest.mark.slow


@pytest.fixture()
def registry(tmp_path, monkeypatch):
    monkeypatch.setenv("FEDML_TPU_RUNS_DIR", str(tmp_path / "runs"))
    monkeypatch.setenv("FEDML_TPU_LOCAL_SLOTS", "2")
    _reset_default_db()
    yield tmp_path
    _reset_default_db()


def _yaml(tmp_path, body, name="job.yaml"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


class TestResourceDB:
    def test_register_match_allocate_release(self, registry):
        db = ResourceDB(str(registry / "r.db"))
        db.register_device("tpu-a", 4)
        db.register_device("tpu-b", 2)
        # matcher: most free slots that fit
        assert db.match(3) == "tpu-a"
        assert db.allocate("run1", 3) == "tpu-a"
        assert db.free_slots("tpu-a") == 1
        # next 2-slot job must land on b (a has only 1 free)
        assert db.allocate("run2", 2) == "tpu-b"
        # nothing fits 2 anymore
        assert db.allocate("run3", 2) is None
        assert db.release("run1") is True
        assert db.free_slots("tpu-a") == 4
        assert db.allocate("run3", 2) == "tpu-a"
        allocs = {a["run_id"]: a["device_id"] for a in db.allocations()}
        assert allocs == {"run2": "tpu-b", "run3": "tpu-a"}

    def test_release_unknown_is_false(self, registry):
        db = ResourceDB(str(registry / "r2.db"))
        assert db.release("nope") is False


class TestLaunchIntegration:
    def test_launch_claims_and_releases_capacity(self, registry):
        from fedml_tpu.api.scheduler import default_db
        yml = _yaml(registry, """
            job: sleep 30
            workspace: .
            computing:
              device_slots: 2
        """)
        res = api.launch_job(yml)
        assert res.result_code == 0
        db = default_db()
        assert db.free_slots("local") == 0
        # a second 1-slot job cannot fit
        yml2 = _yaml(registry, """
            job: "true"
            workspace: .
            computing:
              device_slots: 1
        """, name="job2.yaml")
        res2 = api.launch_job(yml2)
        assert res2.result_code != 0
        assert "free slots" in res2.result_message
        # stopping the first job frees the capacity
        api.run_stop(res.run_id)
        assert db.free_slots("local") == 2
        res3 = api.launch_job(yml2)
        assert res3.result_code == 0
        api.run_wait(res3.run_id, timeout_s=30)
        assert db.free_slots("local") == 2  # finalize released it


class TestJobMonitor:
    def test_kill_detect_restart(self, registry):
        """Kill a running job's process with SIGKILL: the monitor marks
        the run FAILED, releases its slots, and relaunches it (lineage
        recorded), because the yaml opted in with restart: true."""
        from fedml_tpu.api.scheduler import default_db
        yml = _yaml(registry, """
            job: sleep 60
            workspace: .
            restart: true
            computing:
              device_slots: 1
        """)
        res = api.launch_job(yml)
        assert res.result_code == 0
        assert api.run_status(res.run_id) == api.STATUS_RUNNING
        mon = JobMonitor(interval_s=0.2, max_restarts=2)
        mon.start()
        try:
            os.killpg(os.getpgid(res.inner_id), signal.SIGKILL)
            deadline = time.time() + 15
            while time.time() < deadline and res.run_id not in mon.restarted:
                time.sleep(0.1)
            assert res.run_id in mon.restarted, "monitor never restarted"
            new_id = mon.restarted[res.run_id]
            assert api.run_status(res.run_id) == api.STATUS_FAILED
            assert api.run_status(new_id) == api.STATUS_RUNNING
            meta = api._read_meta(new_id)
            assert meta["restart_of"] == res.run_id
            # capacity: dead run released, replacement claimed -> 1 used
            assert default_db().free_slots("local") == 1
            api.run_stop(new_id)
        finally:
            mon.stop()

    def test_max_restarts_bounds_crash_loops(self, registry):
        """A job that dies instantly is restarted at most max_restarts
        times across its lineage."""
        yml = _yaml(registry, """
            job: sleep 60
            workspace: .
            restart: true
        """)
        res = api.launch_job(yml)
        mon = JobMonitor(interval_s=0.15, max_restarts=2)
        mon.start()
        try:
            current = res.run_id
            killed = [current]
            deadline = time.time() + 30
            while time.time() < deadline and len(mon.restarted) < 2:
                meta = api._read_meta(current)
                pid = int(meta.get("pid", -1))
                if (api.run_status(current) == api.STATUS_RUNNING
                        and pid > 0):
                    try:
                        os.killpg(os.getpgid(pid), signal.SIGKILL)
                    except OSError:
                        pass
                if current in mon.restarted:
                    current = mon.restarted[current]
                    killed.append(current)
                time.sleep(0.1)
            assert len(mon.restarted) == 2
            # kill the last one too: no further restart beyond the cap
            meta = api._read_meta(current)
            pid = int(meta.get("pid", -1))
            if pid > 0 and api.run_status(current) == api.STATUS_RUNNING:
                try:
                    os.killpg(os.getpgid(pid), signal.SIGKILL)
                except OSError:
                    pass
            time.sleep(1.5)
            assert len(mon.restarted) == 2  # capped
        finally:
            mon.stop()

    def test_restart_fires_after_external_finalize(self, registry):
        """A status poller may reconcile the dead run to FAILED before
        the monitor's scan — crash detection is exit-record based, so the
        restart must still fire exactly once."""
        yml = _yaml(registry, """
            job: sleep 60
            workspace: .
            restart: true
        """)
        res = api.launch_job(yml)
        os.killpg(os.getpgid(res.inner_id), signal.SIGKILL)
        os.waitpid(res.inner_id, 0)  # reap: in a real deployment init does
        assert api.run_status(res.run_id) == api.STATUS_FAILED  # poller won
        mon = JobMonitor(interval_s=0.2, max_restarts=2)
        acted = mon.scan_once()
        assert acted == [res.run_id]
        assert res.run_id in mon.restarted
        # a second scan (or a second monitor) must not restart it again
        assert mon.scan_once() == []
        mon2 = JobMonitor(interval_s=0.2, max_restarts=2)
        assert mon2.scan_once() == []
        api.run_stop(mon.restarted[res.run_id])

    def test_restart_cap_persists_across_monitor_restarts(self, registry):
        """restart_index lives in run meta: a fresh monitor process must
        not grant a crash-looping lineage a new budget."""
        yml = _yaml(registry, """
            job: sleep 60
            workspace: .
            restart: true
        """)
        res = api.launch_job(yml)
        current = res.run_id
        for expected_idx in (1, 2):
            meta = api._read_meta(current)
            os.killpg(os.getpgid(int(meta["pid"])), signal.SIGKILL)
            time.sleep(0.3)
            mon = JobMonitor(interval_s=0.2, max_restarts=2)  # fresh each time
            mon.scan_once()
            assert current in mon.restarted
            current = mon.restarted[current]
            assert api._read_meta(current)["restart_index"] == expected_idx
        # cap reached: a third fresh monitor refuses
        meta = api._read_meta(current)
        os.killpg(os.getpgid(int(meta["pid"])), signal.SIGKILL)
        time.sleep(0.3)
        mon = JobMonitor(interval_s=0.2, max_restarts=2)
        acted = mon.scan_once()
        assert acted == [current] and current not in mon.restarted

    def test_monitor_ignores_healthy_and_finished_runs(self, registry):
        yml = _yaml(registry, """
            job: "true"
            workspace: .
        """)
        res = api.launch_job(yml)
        api.run_wait(res.run_id, timeout_s=30)
        mon = JobMonitor(interval_s=0.2)
        acted = mon.scan_once()
        assert acted == []
        assert api.run_status(res.run_id) == api.STATUS_FINISHED
