"""Security: robust-aggregation kernels, attack->defense e2e, SP/TPU parity
under attack, and the gradient-inversion (DLG) privacy demo."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.security.defense import robust_agg


def make_updates(k=10, d=20, byz=2, seed=0, shift=50.0):
    """Honest updates cluster near a true direction; byzantine are far off."""
    rng = np.random.RandomState(seed)
    true = rng.randn(d).astype(np.float32)
    ups = true[None] + 0.1 * rng.randn(k, d).astype(np.float32)
    ups[:byz] = shift * rng.randn(byz, d)
    return jnp.asarray(ups), jnp.ones((k,)), true


class TestKernels:
    def test_krum_rejects_byzantine(self):
        ups, w, true = make_updates()
        agg, info = robust_agg.krum(ups, w, byzantine_count=2)
        assert np.linalg.norm(np.asarray(agg) - true) < 1.0
        assert np.asarray(info["selected"])[:2].sum() == 0  # byz not selected

    def test_multi_krum(self):
        ups, w, true = make_updates()
        agg, info = robust_agg.krum(ups, w, byzantine_count=2, multi_k=5)
        assert np.linalg.norm(np.asarray(agg) - true) < 1.0

    def test_median_and_trimmed_mean(self):
        ups, w, true = make_updates()
        for fn in (robust_agg.coordinate_median,
                   lambda u, ww: robust_agg.trimmed_mean(u, ww, 0.25)):
            agg = fn(ups, w)[0]
            assert np.linalg.norm(np.asarray(agg) - true) < 1.0

    def test_geometric_median(self):
        ups, w, true = make_updates()
        agg, _ = robust_agg.geometric_median(ups, w, iters=32)
        assert np.linalg.norm(np.asarray(agg) - true) < 1.0

    def test_bulyan(self):
        ups, w, true = make_updates(k=12, byz=2)
        agg, _ = robust_agg.bulyan(ups, w, byzantine_count=2)
        assert np.linalg.norm(np.asarray(agg) - true) < 1.0

    def test_three_sigma_and_outlier(self):
        ups, w, true = make_updates()
        for fn in (robust_agg.three_sigma, robust_agg.outlier_detection,
                   robust_agg.residual_reweight):
            agg, info = fn(ups, w)
            assert np.linalg.norm(np.asarray(agg) - true) < 1.5, fn

    def test_norm_clip_bounds(self):
        ups, w, _ = make_updates()
        agg, _ = robust_agg.norm_clip(ups, w, max_norm=1.0)
        assert np.linalg.norm(np.asarray(agg)) <= 1.0 + 1e-5

    def test_centered_clip(self):
        ups, w, true = make_updates()
        agg, _ = robust_agg.centered_clip(ups, w, tau=5.0, iters=5)
        assert np.linalg.norm(np.asarray(agg) - true) < 2.0

    def test_foolsgold_downweights_sybils(self):
        rng = np.random.RandomState(0)
        honest = rng.randn(5, 30).astype(np.float32)
        sybil = np.tile(rng.randn(1, 30).astype(np.float32), (3, 1))
        hist = jnp.asarray(np.concatenate([sybil, honest]))
        wv = np.asarray(robust_agg.foolsgold_weights(hist))
        assert wv[:3].mean() < 0.1 * max(wv[3:].mean(), 1e-6) + 0.05

    def test_rlr_flips_disagreement(self):
        ups = jnp.asarray(np.array([[1.0, 1.0], [1.0, -1.0], [1.0, 1.0],
                                    [-1.0, -1.0]], np.float32))
        w = jnp.ones((4,))
        agg, info = robust_agg.robust_learning_rate(ups, w, threshold=2)
        # coord 0: 3 vs 1 agreement (|sum|=2) -> keep; coord 1: 2 vs 2 -> flip
        assert np.asarray(info["lr_sign"]).tolist() == [1.0, -1.0]


def sim_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=8, client_num_per_round=8,
                comm_round=6, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=3, random_seed=3)
    base.update(kw)
    return Arguments(**base)


class TestEndToEnd:
    def test_byzantine_hurts_and_krum_recovers(self):
        clean = fedml_tpu.run_simulation(backend="tpu", args=sim_args())
        attacked = fedml_tpu.run_simulation(backend="tpu", args=sim_args(
            enable_attack=True, attack_type="byzantine_random",
            byzantine_client_num=3, attack_scale=20.0))
        defended = fedml_tpu.run_simulation(backend="tpu", args=sim_args(
            enable_attack=True, attack_type="byzantine_random",
            byzantine_client_num=3, attack_scale=20.0,
            enable_defense=True, defense_type="multi_krum", krum_param_m=3))
        assert attacked["final_test_acc"] < clean["final_test_acc"] - 0.1
        # multi-Krum (m=3) averages the lowest-score honest picks; single-Krum
        # follows one client per round and its short-horizon accuracy swings
        # ~0.5-0.9 with the batch-order seed, which is too fragile to gate on
        assert defended["final_test_acc"] > attacked["final_test_acc"] + 0.1
        assert defended["final_test_acc"] > 0.8

    def test_sp_tpu_parity_under_attack_defense(self):
        kw = dict(enable_attack=True, attack_type="byzantine_flip",
                  byzantine_client_num=2, attack_scale=5.0,
                  enable_defense=True, defense_type="coordinate_median",
                  comm_round=3)
        r_sp = fedml_tpu.run_simulation(backend="sp", args=sim_args(**kw))
        r_tpu = fedml_tpu.run_simulation(backend="tpu", args=sim_args(**kw))
        for a, b in zip(jax.tree_util.tree_leaves(r_sp["params"]),
                        jax.tree_util.tree_leaves(r_tpu["params"])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5)

    def test_label_flip_poisoning_degrades(self):
        clean = fedml_tpu.run_simulation(backend="tpu", args=sim_args())
        poisoned = fedml_tpu.run_simulation(backend="tpu", args=sim_args(
            enable_attack=True, attack_type="label_flip",
            byzantine_client_num=6))
        assert poisoned["final_test_acc"] < clean["final_test_acc"] + 0.02


class TestGradientInversion:
    def test_dlg_recovers_input_on_lr(self):
        from fedml_tpu.core.security.dlg import invert_gradient
        from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
        from fedml_tpu.model import create as create_model

        args = sim_args()
        bundle = create_model(args, 10)
        spec = ClassificationTrainer(bundle.apply)
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(jax.random.fold_in(rng, 1), (1, 784))
        y = jnp.asarray([3])
        params = bundle.init(jax.random.fold_in(rng, 2), x)
        batch = {"x": x, "y": y, "mask": jnp.ones((1,))}
        grads, _ = jax.grad(spec.loss, has_aux=True)(params, batch, rng)
        out = invert_gradient(spec, params, grads, (1, 784), 10,
                              jax.random.fold_in(rng, 3), steps=2000, lr=0.05)
        rec = np.asarray(out["x"][0])
        truth = np.asarray(x[0])
        cos = np.dot(rec, truth) / (np.linalg.norm(rec) * np.linalg.norm(truth))
        assert cos > 0.8, cos
        assert int(np.argmax(np.asarray(out["y_logits"][0]))) == 3


class TestNewAttacksDefenses:
    def test_lazy_worker_attack_filtered_by_wbc(self):
        import fedml_tpu
        args = Arguments(dataset="synthetic_mnist", model="lr",
                         client_num_in_total=8, client_num_per_round=8,
                         comm_round=4, batch_size=32, learning_rate=0.1,
                         random_seed=2, enable_attack=True,
                         attack_type="lazy_worker", byzantine_client_num=3,
                         enable_defense=True, defense_type="wbc",
                         frequency_of_the_test=3)
        r = fedml_tpu.run_simulation(backend="tpu", args=args)
        assert r["final_test_acc"] > 0.55, r["history"]

    def test_backdoor_poisons_data_and_krum_defends(self):
        import fedml_tpu
        base = dict(dataset="synthetic_mnist", model="lr",
                    client_num_in_total=8, client_num_per_round=8,
                    comm_round=4, batch_size=32, learning_rate=0.1,
                    random_seed=2, enable_attack=True,
                    attack_type="backdoor", byzantine_client_num=2,
                    backdoor_target_label=0, frequency_of_the_test=3)
        r = fedml_tpu.run_simulation(
            backend="tpu", args=Arguments(enable_defense=True,
                                          defense_type="krum", **base))
        assert r["final_test_acc"] > 0.5, r["history"]

    def test_backdoor_stamp_shapes(self):
        from fedml_tpu.core.security.attack import backdoor_stamp
        flat = np.zeros((5, 784), np.float32)
        out = backdoor_stamp(flat)
        assert out[:, :9].min() == 1.0 and out[:, 9:].max() == 0.0
        img = np.zeros((5, 8, 8, 3), np.float32)
        out = backdoor_stamp(img)
        assert out[:, :3, :3, :].min() == 1.0
        assert out[:, 3:, 3:, :].max() == 0.0

    def test_soteria_and_cross_round_run(self):
        from fedml_tpu.core.security.defense import FedMLDefender
        rs = np.random.RandomState(0)
        mat = jnp.asarray(rs.randn(6, 40).astype(np.float32))
        w = jnp.ones(6)
        for d in ("soteria", "wbc"):
            dfd = FedMLDefender(Arguments(enable_defense=True,
                                          defense_type=d))
            vec, _ = dfd.defend_matrix(mat, w)
            assert vec.shape == (40,) and np.isfinite(np.asarray(vec)).all()
        # cross_round: an oscillating client is dropped in round 2
        dfd = FedMLDefender(Arguments(enable_defense=True,
                                      defense_type="cross_round"))
        ids = np.arange(6)
        v1, _ = dfd.defend_matrix(mat, w, client_ids=ids)
        flip = mat.at[0].set(-mat[0])  # client 0 reverses direction
        v2, info = dfd.defend_matrix(flip, w, client_ids=ids)
        assert float(info["kept"]) == 5.0


class TestShardedDefense:
    def test_sharded_matches_host(self):
        """Feature-sharded defense == host defense on an 8-device mesh."""
        import jax
        from fedml_tpu.core.mesh import build_mesh
        from fedml_tpu.core.security.defense import sharded
        from fedml_tpu.core.security.defense import robust_agg
        mesh = build_mesh({"client": 8})
        rs = np.random.RandomState(3)
        mat = jnp.asarray(rs.randn(10, 123).astype(np.float32))
        w = jnp.asarray(rs.rand(10).astype(np.float32) + 0.5)
        cases = {
            "krum": lambda: robust_agg.krum(mat, w, 2, 1)[0],
            "multi_krum": lambda: robust_agg.krum(mat, w, 2, 3)[0],
            "median": lambda: robust_agg.coordinate_median(mat, w)[0],
            "trimmed_mean": lambda: robust_agg.trimmed_mean(mat, w, 0.1)[0],
            "three_sigma": lambda: robust_agg.three_sigma(mat, w)[0],
            # ISSUE 4: the formerly host-only stateless defenses
            "bulyan": lambda: robust_agg.bulyan(mat, w, 2)[0],
            "rfa": lambda: robust_agg.geometric_median(mat, w)[0],
            "norm_clip": lambda: robust_agg.norm_clip(mat, w, 5.0)[0],
            "outlier_detection":
                lambda: robust_agg.outlier_detection(mat, w)[0],
            "residual_reweight":
                lambda: robust_agg.residual_reweight(mat, w)[0],
            "rlr": lambda: robust_agg.robust_learning_rate(mat, w)[0],
            "wbc": lambda: robust_agg.wbc(mat, w)[0],
            "soteria": lambda: robust_agg.soteria(mat, w, 0.5)[0],
        }
        for d, host_fn in cases.items():
            out = sharded.defend_matrix_sharded(
                mesh, "client", mat, w, d, byzantine_count=2, multi_k=3)
            assert out.shape == (123,)
            # the big axis stays sharded until we pull it
            if host_fn is not None:
                np.testing.assert_allclose(np.asarray(out),
                                           np.asarray(host_fn()),
                                           rtol=2e-4, atol=2e-5,
                                           err_msg=d)

    def test_sharded_stateful_matches_host_across_rounds(self):
        """FoolsGold / cclip / slsgd / cross_round carry cross-round state
        — the sharded kernels must reproduce the host kernels' trajectory
        over several rounds, state threading included."""
        from fedml_tpu.core.mesh import build_mesh
        from fedml_tpu.core.security.defense import robust_agg, sharded
        mesh = build_mesh({"client": 8})
        rs = np.random.RandomState(7)
        w = jnp.ones(6)
        ids = jnp.arange(6, dtype=jnp.int32)
        mats = [jnp.asarray(rs.randn(6, 50).astype(np.float32))
                for _ in range(3)]

        # foolsgold: accumulated history drives the weights
        hist = np.zeros((6, 50), np.float32)
        state = None
        for m in mats:
            hist[np.arange(6)] += np.asarray(m)
            host_vec, _ = robust_agg.foolsgold(m, w, jnp.asarray(hist))
            out, state = sharded.defend_matrix_sharded(
                mesh, "client", m, w, "foolsgold", state=state, ids=ids)
            np.testing.assert_allclose(np.asarray(out),
                                       np.asarray(host_vec),
                                       rtol=2e-4, atol=2e-5)

        # cclip: momentum carries
        mom, state = None, None
        for m in mats:
            host_vec, _ = robust_agg.centered_clip(m, w, 10.0, momentum=mom)
            mom = host_vec
            out, state = sharded.defend_matrix_sharded(
                mesh, "client", m, w, "cclip", state=state)
            np.testing.assert_allclose(np.asarray(out),
                                       np.asarray(host_vec),
                                       rtol=2e-4, atol=2e-5)

        # cross_round: an oscillating client is dropped in round 2
        state = None
        m1 = mats[0]
        _, state = sharded.defend_matrix_sharded(
            mesh, "client", m1, w, "cross_round", state=state, ids=ids)
        m2 = m1.at[0].set(-m1[0])
        host_v2, info = robust_agg.cross_round_filter(
            m2, w, m1, jnp.ones(6))
        assert float(info["kept"]) == 5.0
        v2, state = sharded.defend_matrix_sharded(
            mesh, "client", m2, w, "cross_round", state=state, ids=ids)
        np.testing.assert_allclose(np.asarray(v2), np.asarray(host_v2),
                                   rtol=2e-4, atol=2e-5)

    def test_unknown_defense_error_lists_sharded_names(self):
        """The no-sharded-path ValueError must NAME the supported
        defenses, not just refuse."""
        from fedml_tpu.core.mesh import build_mesh
        from fedml_tpu.core.security.defense import sharded
        mesh = build_mesh({"client": 8})
        with pytest.raises(ValueError) as ei:
            sharded.defend_matrix_sharded(
                mesh, "client", jnp.ones((4, 16)), jnp.ones(4), "bogus")
        msg = str(ei.value)
        assert "bulyan" in msg and "rfa" in msg and "foolsgold" in msg

    def test_engine_uses_sharded_defense(self):
        import fedml_tpu
        args = Arguments(dataset="synthetic_mnist", model="lr",
                         client_num_in_total=8, client_num_per_round=8,
                         comm_round=3, batch_size=32, learning_rate=0.1,
                         random_seed=2, enable_attack=True,
                         attack_type="byzantine_random",
                         byzantine_client_num=2, enable_defense=True,
                         defense_type="multi_krum", krum_param_m=3,
                         sharded_defense=True, frequency_of_the_test=2)
        r = fedml_tpu.run_simulation(backend="tpu", args=args)
        assert r["final_test_acc"] > 0.55, r["history"]

    def test_wbc_keeps_majority_cluster(self):
        """Regression: wbc must aggregate the LARGER (honest) cluster."""
        from fedml_tpu.core.security.defense.robust_agg import wbc
        honest = np.ones((6, 10), np.float32)
        byz = np.zeros((2, 10), np.float32)
        mat = jnp.asarray(np.concatenate([honest, byz]))
        vec, info = wbc(mat, jnp.ones(8))
        assert float(info["kept"]) == 6.0
        np.testing.assert_allclose(np.asarray(vec), np.ones(10), atol=1e-5)


class TestShardedDefault:
    def test_sharded_defense_is_default_and_no_host_materialization(self):
        """With a sharded-capable defense the engine must auto-select the
        feature-sharded path and never pull the [K, D] update matrix to the
        host: the whole robust aggregation runs under a device->host
        transfer guard."""
        import jax as _jax
        from fedml_tpu.arguments import Arguments
        from fedml_tpu.core.algframe.client_trainer import (
            ClassificationTrainer)
        from fedml_tpu.core.algframe.types import TrainHyper
        from fedml_tpu import data as data_mod, model as model_mod
        from fedml_tpu.optimizers.registry import create_optimizer
        from fedml_tpu.simulation.tpu.engine import TPUSimulator

        args = sim_args(enable_attack=True, attack_type="byzantine_flip",
                        byzantine_client_num=2, attack_scale=5.0,
                        enable_defense=True, defense_type="coordinate_median")
        fed, output_dim = data_mod.load(args)
        bundle = model_mod.create(args, output_dim)
        spec = ClassificationTrainer(bundle.apply)
        sim = TPUSimulator(args, fed, bundle,
                           create_optimizer(args, spec), spec)
        assert sim._use_sharded_defense()
        hyper = TrainHyper(learning_rate=jnp.float32(0.1), epochs=1)
        with _jax.transfer_guard_device_to_host("disallow"):
            metrics = sim.run_round(0, hyper)
        assert float(metrics["count"]) > 0  # readback OUTSIDE the guard

    def test_sharded_path_matches_host_path(self):
        """Auto-sharded defended round == forced-host defended round."""
        kw = dict(enable_attack=True, attack_type="byzantine_flip",
                  byzantine_client_num=2, attack_scale=5.0,
                  enable_defense=True, defense_type="coordinate_median",
                  comm_round=2)
        r_auto = fedml_tpu.run_simulation(backend="tpu", args=sim_args(**kw))
        r_host = fedml_tpu.run_simulation(
            backend="tpu", args=sim_args(sharded_defense="false", **kw))
        for a, b in zip(jax.tree_util.tree_leaves(r_auto["params"]),
                        jax.tree_util.tree_leaves(r_host["params"])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
