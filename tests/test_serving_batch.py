"""Continuous-batching LLM serving (ISSUE 9): paged-KV decode bit-parity
vs the full-forward step, compile-once across concurrency/adapter mix,
admit/evict determinism, multi-LoRA adapter isolation, tail truncation,
per-request seeds, gateway p50/p99, and the chat endpoint under
concurrent clients.

Tier-1 except the HTTP/replica/soak tests (slow-marked): the core
correctness claims — parity, compile-once, determinism, isolation — run
in the quick gate.
"""

import concurrent.futures as cf
import threading
import time

import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core.obs import metrics as obs_metrics
from fedml_tpu.llm.federated import build_llm
from fedml_tpu.serving.llm_template import (CausalLMPredictor,
                                            ChatCompletionRunner)

pytestmark = pytest.mark.serving


def _args(**kw):
    base = dict(dataset="llm_synthetic", model="causal_lm",
                client_num_in_total=2, client_num_per_round=2,
                comm_round=1, epochs=1, batch_size=4, learning_rate=1e-3,
                random_seed=3, llm_hidden_size=32, llm_num_layers=2,
                llm_num_heads=2, llm_intermediate_size=64,
                llm_max_seq_len=64, lora_rank=4)
    base.update(kw)
    return Arguments(**base)


def _rand_adapter(template, seed):
    """A LoRA tree with NONZERO lora_b (lora_init zeroes b, which would
    make every adapter a no-op and isolation vacuous)."""
    import jax
    import jax.numpy as jnp
    leaves, treedef = jax.tree_util.tree_flatten(template)
    key = jax.random.PRNGKey(seed)
    out = []
    for i, l in enumerate(leaves):
        k = jax.random.fold_in(key, i)
        out.append(0.3 * jax.random.normal(k, l.shape, jnp.float32))
    return jax.tree_util.tree_unflatten(treedef, out)


@pytest.fixture(scope="module")
def lora_setup():
    """LoRA artifact (bundle.base_params frozen, params = adapter tree):
    the single path serves it MERGED, the batch path serves it FACTORED
    from the adapter bank — parity across that split is the acceptance
    pin."""
    import jax
    args = _args()
    _, bundle, _, tok = build_llm(args)
    params = bundle.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))
    return args, bundle, params, tok


@pytest.fixture(scope="module")
def predictors(lora_setup):
    args, bundle, params, tok = lora_setup
    single = CausalLMPredictor(bundle, params, tokenizer=tok)
    batched = CausalLMPredictor(
        bundle, params, tokenizer=tok, mode="batch",
        batch_opts={"slots": 4, "block_size": 16, "prefill_chunk": 8})
    yield single, batched
    batched.close()


@pytest.fixture(scope="module")
def full_ft_setup():
    """Full fine-tune artifact (lora_rank=0): params ARE the model."""
    import jax
    args = _args(lora_rank=0)
    _, bundle, _, tok = build_llm(args)
    params = bundle.init(jax.random.PRNGKey(1), np.zeros((1, 8), np.int32))
    return args, bundle, params, tok


# ------------------------------------------------------------- parity ----

class TestKVParity:
    """Acceptance pin: paged-KV decode is bit-identical to the original
    full-forward step on the same artifact (greedy)."""

    PROMPTS = ["add 2 3", "echo hello world", "x",
               "subtract 19 4 and then explain"]

    def test_greedy_bit_parity_lora_artifact(self, predictors):
        single, batched = predictors
        for prompt in self.PROMPTS:
            a = single.generate(prompt, max_new_tokens=12)
            b = batched.generate(prompt, max_new_tokens=12)
            assert a["text"] == b["text"], prompt
            assert a["finish_reason"] == b["finish_reason"]
            assert a["completion_tokens"] == b["completion_tokens"]

    def test_greedy_bit_parity_full_ft_artifact(self, full_ft_setup):
        args, bundle, params, tok = full_ft_setup
        single = CausalLMPredictor(bundle, params, tokenizer=tok)
        batched = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 16, "prefill_chunk": 8})
        try:
            for prompt in self.PROMPTS[:3]:
                assert (single.generate(prompt, max_new_tokens=10)["text"]
                        == batched.generate(prompt,
                                            max_new_tokens=10)["text"])
        finally:
            batched.close()

    def test_batching_never_changes_a_request(self, predictors):
        """A seeded request's output is invariant to what else is in
        flight: solo == submitted alongside 3 concurrent neighbours."""
        _, batched = predictors
        solo = batched.generate("add 4 5", max_new_tokens=10,
                                temperature=1.2, seed=77)
        with cf.ThreadPoolExecutor(4) as ex:
            futs = [ex.submit(batched.generate, "add 4 5",
                              max_new_tokens=10, temperature=1.2, seed=77)]
            futs += [ex.submit(batched.generate, f"noise {i} blah blah",
                               max_new_tokens=10, temperature=0.8, seed=i)
                     for i in range(3)]
            crowded = futs[0].result(timeout=120)
        assert crowded["text"] == solo["text"]

    def test_single_mode_knob_keeps_old_path(self, predictors):
        single, _ = predictors
        assert single._engine is None  # no batch machinery constructed
        with pytest.raises(ValueError, match="batch"):
            single.generate("hi", adapter="silo_0")


# ------------------------------------------------------- compile-once ----

class TestCompileOnce:
    def test_decode_compiles_once_across_concurrency_and_adapters(
            self, lora_setup, xla_compile_counter):
        """Occupancy 1→S, admits/evicts, adapter mix, temps, and bank
        growth after warmup: all DATA — zero recompiles."""
        import jax
        from fedml_tpu.serving.batch import AdapterBank, DecodeScheduler

        args, bundle, params, tok = lora_setup
        bank = AdapterBank(params, alpha=bundle.lora_alpha, capacity=8)
        bank.add("a", _rand_adapter(params, 10))
        bank.add("b", _rand_adapter(params, 11))
        sched = DecodeScheduler(bundle.module, bundle.cfg,
                                bundle.base_params, bank, slots=4,
                                block_size=16, prefill_chunk=8)
        ids = [1] + tok.encode("warm up prompt") + [3]
        # warmup: compile prefill + first-token sample + decode step
        slot, _ = sched.admit(ids, adapter_idx=1, temperature=0.7, seed=5,
                              max_new_tokens=4)
        sched.step()
        sched.release(slot)
        xla_compile_counter.reset()
        # bank growth after warmup: capacity padding keeps shapes fixed
        bank.add("c", _rand_adapter(params, 12))
        prompts = ["x", "add 2 3",
                   "a longer prompt spanning chunks"]
        for occupancy in (1, 2, 4):
            slots = [sched.admit([1] + tok.encode(prompts[i % 3]) + [3],
                                 adapter_idx=(i % 4),
                                 temperature=float(i % 2), seed=i,
                                 max_new_tokens=4)[0]
                     for i in range(occupancy)]
            for _ in range(3):
                sched.step()
            for s in slots:
                sched.release(s)
        assert xla_compile_counter.delta() == 0


# ------------------------------------------- admit/evict determinism ----

class TestAdmitEvictDeterminism:
    def _run_sequence(self, lora_setup):
        from fedml_tpu.serving.batch import DecodeScheduler
        args, bundle, params, tok = lora_setup
        sched = DecodeScheduler(bundle.module, bundle.cfg,
                                bundle.base_params, None, slots=3,
                                block_size=16, prefill_chunk=8)
        trace = []
        enc = lambda p: [1] + tok.encode(p) + [3]  # noqa: E731
        s0, t0 = sched.admit(enc("alpha"), seed=1, max_new_tokens=8)
        s1, t1 = sched.admit(enc("beta"), seed=2, max_new_tokens=8)
        trace += [("admit", s0, t0), ("admit", s1, t1)]
        trace.append(("step", tuple(sorted(sched.step().items()))))
        sched.release(s0)
        trace.append(("free", tuple(sched.free_slots())))
        s2, t2 = sched.admit(enc("gamma gamma"), seed=3, max_new_tokens=8)
        trace += [("admit", s2, t2)]
        trace.append(("step", tuple(sorted(sched.step().items()))))
        trace.append(("tables", sched._tables.tolist()))
        return trace

    def test_same_sequence_same_slots_same_tokens(self, lora_setup):
        assert (self._run_sequence(lora_setup)
                == self._run_sequence(lora_setup))

    def test_released_slot_is_reused_lowest_first(self, lora_setup):
        from fedml_tpu.serving.batch import DecodeScheduler
        args, bundle, params, tok = lora_setup
        sched = DecodeScheduler(bundle.module, bundle.cfg,
                                bundle.base_params, None, slots=2,
                                block_size=16, prefill_chunk=8)
        ids = [1] + tok.encode("hi") + [3]
        a, _ = sched.admit(ids, max_new_tokens=4)
        b, _ = sched.admit(ids, max_new_tokens=4)
        assert (a, b) == (0, 1)
        assert not sched.can_admit(len(ids), 4)  # slots full
        sched.release(a)
        c, _ = sched.admit(ids, max_new_tokens=4)
        assert c == 0  # freed slot comes back, deterministically


# ------------------------------------------------- adapter isolation ----

class TestAdapterIsolation:
    @pytest.fixture(scope="class")
    def banked(self, lora_setup):
        args, bundle, params, tok = lora_setup
        batched = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 4, "block_size": 16, "prefill_chunk": 8,
                        "max_adapters": 8})
        batched.adapter_bank.add("siloA", _rand_adapter(params, 20))
        batched.adapter_bank.add("siloB", _rand_adapter(params, 21))
        yield batched
        batched.close()

    def test_adapters_actually_differ(self, banked):
        outs = {name: banked.generate("add 2 3", max_new_tokens=10,
                                      adapter=name)["text"]
                for name in ("siloA", "siloB", "base")}
        assert len(set(outs.values())) == 3, outs

    def test_routed_request_never_sees_other_adapter(self, banked):
        """Concurrent mixed-adapter batch: every request's output equals
        its solo run — adapter A's weights never leak into B's slots."""
        solo = {n: banked.generate("echo zq", max_new_tokens=10,
                                   adapter=n)["text"]
                for n in ("siloA", "siloB", "base")}
        names = ["siloA", "siloB", "base", "siloA"]
        with cf.ThreadPoolExecutor(4) as ex:
            futs = [ex.submit(banked.generate, "echo zq",
                              max_new_tokens=10, adapter=n)
                    for n in names]
            outs = [f.result(timeout=120) for f in futs]
        for n, o in zip(names, outs):
            assert o["text"] == solo[n], n

    def test_unknown_adapter_raises_not_silently_serves(self, banked):
        with pytest.raises(KeyError, match="unknown adapter"):
            banked.generate("hi", adapter="nonexistent_silo")

    def test_base_adapter_is_reserved(self, banked):
        with pytest.raises(ValueError, match="reserved"):
            banked.adapter_bank.add("base", _rand_adapter(
                banked.params, 30))

    def test_bank_capacity_enforced(self, lora_setup):
        from fedml_tpu.serving.batch import AdapterBank
        _, bundle, params, _ = lora_setup
        bank = AdapterBank(params, capacity=2)
        bank.add("one", _rand_adapter(params, 1))
        with pytest.raises(RuntimeError, match="full"):
            bank.add("two", _rand_adapter(params, 2))

    def test_adapter_request_without_bank_raises(self, full_ft_setup):
        """Full fine-tune batch mode has no bank: a named adapter must
        error, never silently serve the base model as someone's
        personalization."""
        args, bundle, params, tok = full_ft_setup
        batched = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 16, "prefill_chunk": 8})
        try:
            with pytest.raises(ValueError, match="no adapter bank"):
                batched.generate("hi", adapter="silo_0")
        finally:
            batched.close()

    def test_lora_stack_select_and_zero(self, lora_setup):
        """The lora.py bank primitives: stack N adapters into one [A,...]
        pytree, gather per-slot trees back out, and the content-free
        identity adapter."""
        import jax
        import jax.numpy as jnp
        from fedml_tpu.llm.lora import (lora_select, lora_stack,
                                        lora_zero_like)
        _, _, params, _ = lora_setup
        adapters = [params, _rand_adapter(params, 70),
                    lora_zero_like(params)]
        stack = lora_stack(adapters)
        for leaf, src in zip(jax.tree_util.tree_leaves(stack),
                             jax.tree_util.tree_leaves(params)):
            assert leaf.shape == (3,) + src.shape
        # scalar select returns adapter i exactly
        sel = lora_select(stack, jnp.int32(1))
        for a, b in zip(jax.tree_util.tree_leaves(sel),
                        jax.tree_util.tree_leaves(adapters[1])):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        # batched select gathers per-slot trees with a leading [S] axis
        batched = lora_select(stack, jnp.asarray([2, 0], jnp.int32))
        for leaf in jax.tree_util.tree_leaves(batched):
            assert leaf.shape[0] == 2
            assert float(jnp.abs(leaf[0]).sum()) == 0.0  # the zero row
        with pytest.raises(ValueError):
            lora_stack([])


# -------------------------------------------------- adapter artifacts ----

class TestAdapterArtifacts:
    def test_export_load_bank_round_trip(self, lora_setup, tmp_path):
        import jax
        from fedml_tpu.llm.federated import (load_adapter_artifacts,
                                             save_adapter_artifacts)
        from fedml_tpu.serving.batch import AdapterBank
        _, bundle, params, _ = lora_setup
        adapters = {"global": params,
                    "silo_0": _rand_adapter(params, 40),
                    "silo/../1": _rand_adapter(params, 41)}  # hostile name
        manifest = save_adapter_artifacts(adapters, str(tmp_path),
                                          lora_rank=4, lora_alpha=16.0)
        assert manifest.endswith("manifest.json")
        loaded = load_adapter_artifacts(str(tmp_path))
        assert set(loaded) == set(adapters)
        for name in adapters:
            a = jax.tree_util.tree_leaves(adapters[name])
            b = jax.tree_util.tree_leaves(loaded[name])
            assert all(np.array_equal(x, np.asarray(y))
                       for x, y in zip(a, b))
        bank = AdapterBank.from_artifacts(str(tmp_path))
        assert bank.has("global") and bank.has("silo_0")
        assert bank.index("silo_0") > 0

    def test_full_manifest_leaves_room_for_served_artifact(
            self, lora_setup, tmp_path):
        """A manifest that exactly fills the requested capacity must
        still leave a row for the predictor's own 'default' adapter
        (the off-by-one that would crash full-fleet deployments)."""
        from fedml_tpu.llm.federated import save_adapter_artifacts
        from fedml_tpu.serving.batch import AdapterBank
        _, _, params, _ = lora_setup
        save_adapter_artifacts(
            {f"silo_{i}": _rand_adapter(params, 80 + i)
             for i in range(3)}, str(tmp_path))
        bank = AdapterBank.from_artifacts(str(tmp_path), capacity=4)
        bank.add("default", params)  # what _build_engine does

    def test_hostile_names_stay_inside_the_dir(self, lora_setup, tmp_path):
        from fedml_tpu.llm.federated import save_adapter_artifacts
        _, _, params, _ = lora_setup
        out = tmp_path / "bank"
        save_adapter_artifacts({"../escape": params}, str(out))
        files = {p.name for p in out.iterdir()}
        assert files == {"manifest.json", ".._escape.fmtpu"}
        assert not (tmp_path / "escape.fmtpu").exists()


# --------------------------------------------------- engine behaviour ----

class TestEngine:
    def test_eight_concurrent_clients_four_slots(self, predictors):
        """More clients than slots: iteration-level scheduling drains the
        queue; every request resolves with a coherent finish."""
        _, batched = predictors
        with cf.ThreadPoolExecutor(8) as ex:
            outs = list(ex.map(
                lambda i: batched.generate(f"add {i} {i}",
                                           max_new_tokens=8),
                range(8)))
        assert all(o["finish_reason"] in ("stop", "length") for o in outs)
        assert all(o["completion_tokens"] <= 8 for o in outs)
        # identical prompts got identical greedy answers regardless of
        # admission order
        same = [batched.generate("add 3 3", max_new_tokens=8)["text"]
                for _ in range(2)]
        assert same[0] == same[1]

    def test_deadline_eviction_finishes_with_deadline(self, lora_setup):
        """Satellite (ISSUE 11): a deadline eviction resolves with
        finish_reason "deadline" — clients can tell "budget spent"
        ("length") apart from "truncated by the server"."""
        _, bundle, params, tok = lora_setup
        batched = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 16, "prefill_chunk": 8})
        try:
            evicted = obs_metrics.REGISTRY.counter(
                "llm_requests_evicted_total",
                labels=("reason",)).value(reason="deadline")
            fut = batched._engine.submit(
                [1] + tok.encode("a long story about") + [3],
                max_new_tokens=60, temperature=0.5, seed=9,
                deadline_s=0.05)
            out = fut.result(timeout=30)
            assert out["finish_reason"] == "deadline"
            assert out["completion_tokens"] < 60
            after = obs_metrics.REGISTRY.counter(
                "llm_requests_evicted_total",
                labels=("reason",)).value(reason="deadline")
            assert after >= evicted  # counted unless it raced to finish
        finally:
            batched.close()

    def test_infeasible_request_fails_fast_not_wedged(self, lora_setup):
        """A request whose worst-case KV reservation exceeds the whole
        pool must fail at submit, not sit unadmittable at the queue head
        blocking everyone behind it."""
        _, bundle, params, tok = lora_setup
        batched = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 16, "prefill_chunk": 8,
                        "num_blocks": 2})  # pool: 32 token positions
        try:
            big = batched._engine.submit(
                [1] + tok.encode("a prompt needing many blocks") + [3],
                max_new_tokens=40)
            with pytest.raises(ValueError, match="KV blocks"):
                big.result(timeout=5)
            # the queue is not wedged: a feasible request still serves
            small = batched._engine.submit([1, 90, 3], max_new_tokens=4)
            assert small.result(timeout=30)["finish_reason"] in (
                "stop", "length")
        finally:
            batched.close()

    def test_export_misconfig_fails_before_training(self, lora_setup):
        """lora_rank=0 + llm_adapter_export_dir must raise BEFORE the
        federated run, not discard a finished run's result."""
        from fedml_tpu.llm.federated import run_federated_llm
        args = _args(lora_rank=0)
        args.llm_adapter_export_dir = "/tmp/never_written"
        with pytest.raises(ValueError, match="lora_rank"):
            run_federated_llm(args)

    def test_stopped_engine_rejects_submissions(self, lora_setup):
        _, bundle, params, tok = lora_setup
        batched = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 16, "prefill_chunk": 8})
        eng = batched._engine
        batched.close()
        with pytest.raises(RuntimeError, match="stopped"):
            eng.submit([1, 5, 3], max_new_tokens=4)

    def test_serving_metrics_flow_to_registry(self, predictors):
        _, batched = predictors
        batched.generate("metrics probe", max_new_tokens=4)
        snap = obs_metrics.REGISTRY.snapshot()
        assert "llm_tokens_per_s" in snap
        assert "llm_slot_occupancy" in snap
        assert snap["llm_requests_admitted_total"]["values"][0]["value"] > 0


# ------------------------------------------------ prompt truncation ----

class TestPromptTruncation:
    def test_overlong_prompt_keeps_tail_and_reserves_room(self, predictors):
        """Regression (satellite 1): the old code kept
        ``ids[: max_seq_len - 1]`` — the HEAD — silently dropping the most
        recent chat turns, and left no room for the completion."""
        single, _ = predictors
        prompt = ("OLD" * 40) + " RECENT TAIL"
        ids = single._encode_prompt(prompt, max_new_tokens=16)
        assert len(ids) <= single.max_seq_len - 16
        tail = bytes(t - 4 for t in ids[-10:-1]).decode("latin-1")
        assert "NT TAIL" in tail  # the byte-tokenizer offset is +4
        out = single.generate(prompt, max_new_tokens=16)
        assert out["prompt_tokens"] <= single.max_seq_len - 16
        assert out["completion_tokens"] >= 1

    def test_short_prompt_untouched(self, predictors):
        single, _ = predictors
        ids = single._encode_prompt("hi", max_new_tokens=16)
        assert bytes(t - 4 for t in ids[1:-1]).decode("latin-1") == "hi"

    def test_batch_path_accepts_overlong_prompt(self, predictors):
        _, batched = predictors
        out = batched.generate("Z" * 500, max_new_tokens=8)
        assert out["finish_reason"] in ("stop", "length")


# ------------------------------------------------------ seeding ----

class TestSeeds:
    def test_default_seed_varies_per_request(self, predictors):
        """Satellite 2: no-seed sampled requests must not share one PRNG
        stream (the old ``seed=0`` default gave every user the same
        'sample')."""
        single, _ = predictors
        outs = {single.generate("sample me", max_new_tokens=12,
                                temperature=2.0)["text"]
                for _ in range(4)}
        assert len(outs) > 1

    def test_explicit_seed_reproducible_both_modes(self, predictors):
        single, batched = predictors
        for p in (single, batched):
            a = p.generate("reproduce", max_new_tokens=10,
                           temperature=1.3, seed=42)
            b = p.generate("reproduce", max_new_tokens=10,
                           temperature=1.3, seed=42)
            assert a["text"] == b["text"]

    def test_predict_surface_seed_semantics(self, predictors):
        single, _ = predictors
        base = {"prompt": "surface", "max_new_tokens": 10,
                "temperature": 2.0}
        a = single.predict(dict(base, seed=7))
        b = single.predict(dict(base, seed=7))
        assert a["text"] == b["text"]
        outs = {single.predict(dict(base))["text"] for _ in range(4)}
        assert len(outs) > 1


# ----------------------------------------------- gateway tail latency ----

class TestGatewayTail:
    def test_metrics_expose_p50_p99_and_legacy_unpack(self):
        """The dedupe regression pin: gateway tail stats come from the
        ONE shared core/obs LatencyWindow, and the legacy ``(qps, mean)``
        tuple-unpack of metrics() still works."""
        from fedml_tpu.serving.autoscale import Gateway
        gw = Gateway.__new__(Gateway)
        gw.window_s = 60.0
        gw._lock = threading.Lock()
        gw._window = obs_metrics.LatencyWindow(window_s=60.0)
        now = time.time()
        for l in [0.01] * 98 + [0.5, 2.0]:
            gw._window.observe(l, ts=now)
        m = gw.metrics()
        assert m.p50 == 0.01
        assert m.p99 == 0.5           # nearest-rank tail the mean hides
        assert m.latency_s < 0.05     # mean is tiny
        qps, lat = m                  # legacy tuple unpack still works
        assert (qps, lat) == (m.qps, m.latency_s)
        assert m.signal("p99") == m.p99

    def test_gateway_window_is_the_shared_implementation(self):
        """One source of truth: a live Gateway's window IS the core/obs
        LatencyWindow (no parallel percentile code path to drift)."""
        from fedml_tpu.serving.autoscale import Gateway

        class _RS:
            def ports(self):
                return []
        gw = Gateway(_RS(), window_s=3.0)
        assert isinstance(gw._window, obs_metrics.LatencyWindow)
        assert gw._window.window_s == 3.0
        assert gw.metrics().count == 0

    def test_autoscaler_feeds_declared_latency_signal(self):
        from fedml_tpu.serving.autoscale import (Autoscaler,
                                                 GatewayMetrics)

        class _RS:
            def health_check(self):
                return 0

            def scale_to(self, n):
                return n

            def __len__(self):
                return 1

        class _GW:
            replica_set = _RS()

            def metrics(self):
                return GatewayMetrics(qps=10.0, latency_s=0.02, p50=0.01,
                                      p99=1.0, count=100)

        seen = {}

        class _Policy:
            latency_signal = "p99"

            def desired_replicas(self, qps, latency_s, current):
                seen["lat"] = latency_s
                return current

        Autoscaler(_GW(), _Policy()).step()
        assert seen["lat"] == 1.0  # p99, not the 0.02 mean

    def test_lookback_policy_tail_guard(self):
        from fedml_tpu.serving.autoscale import LookbackPolicy
        p = LookbackPolicy(target_qps_per_replica=10.0, window=5,
                           max_latency_s=0.5)
        assert p.desired_replicas(5.0, 0.1, 2) == 1   # tail fine: demand
        assert p.desired_replicas(5.0, 0.9, 2) == 3   # tail blown: +1
        assert p.latency_signal == "p99"

    def test_gateway_records_obs_histogram(self):
        from fedml_tpu.serving.autoscale import Gateway, ReplicaSet

        class _Echo:
            def predict(self, request):
                return {"ok": 1}

            def ready(self):
                return True

        rs = ReplicaSet(lambda: _Echo(), min_replicas=1, max_replicas=1)
        gw = Gateway(rs, window_s=2.0)
        try:
            # ensure the histogram exists with the seam's own buckets
            # (a bare re-get with defaults would conflict)
            obs_metrics.record_gateway_latency(0.001)
            before = sum(
                v["count"] for v in obs_metrics.REGISTRY.histogram(
                    "serving_gateway_latency_seconds").snapshot())
            gw.predict({"x": 1})
            after = sum(
                v["count"] for v in obs_metrics.REGISTRY.histogram(
                    "serving_gateway_latency_seconds").snapshot())
            assert after == before + 1
        finally:
            rs.stop()


# --------------------------------------------------- HTTP e2e (slow) ----

@pytest.mark.slow
class TestChatEndpointE2E:
    def test_eight_concurrent_chat_clients_with_adapter_mix(
            self, lora_setup):
        import json
        import urllib.request
        args, bundle, params, tok = lora_setup
        predictor = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 4, "block_size": 16, "prefill_chunk": 8,
                        "max_adapters": 8})
        predictor.adapter_bank.add("siloA", _rand_adapter(params, 50))
        predictor.adapter_bank.add("siloB", _rand_adapter(params, 51))
        runner = ChatCompletionRunner(predictor)
        port = runner.start()
        solo = {n: predictor.generate("ping", max_new_tokens=8,
                                      adapter=n)["text"]
                for n in ("siloA", "siloB")}

        def post(i):
            model = ["siloA", "siloB"][i % 2]  # bank entry via model name
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/chat/completions",
                data=json.dumps({
                    "model": model,
                    "messages": [{"role": "user", "content": "ping"}],
                    "max_tokens": 8}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                return model, json.load(r)

        try:
            with cf.ThreadPoolExecutor(8) as ex:
                outs = list(ex.map(post, range(8)))
            for model, out in outs:
                assert out["object"] == "chat.completion"
                assert out["choices"][0]["finish_reason"] in ("stop",
                                                              "length")
                # greedy + adapter routed by model name == solo output
                assert (out["choices"][0]["message"]["content"]
                        == solo[model])
        finally:
            runner.stop()
            predictor.close()


@pytest.mark.slow
class TestReplicaCrash:
    def test_crash_mid_stream_surfaces_cleanly_then_heals(self,
                                                          lora_setup):
        """A replica dying mid-request must yield a clean gateway error
        within the timeout (no hang, no garbage response); the health
        check then replaces it and traffic resumes."""
        from fedml_tpu.serving.autoscale import Gateway, ReplicaSet
        args, bundle, params, tok = lora_setup

        class _SlowPredictor(CausalLMPredictor):
            def chat(self, request):
                time.sleep(0.6)  # hold the request so the crash lands
                return super().chat(request)

        rs = ReplicaSet(
            predictor_factory=lambda: _SlowPredictor(
                bundle, params, tokenizer=tok, mode="batch",
                batch_opts={"slots": 2, "block_size": 16,
                            "prefill_chunk": 8}),
            min_replicas=1, max_replicas=2,
            runner_cls=ChatCompletionRunner)
        gw = Gateway(rs, window_s=5.0)
        req = {"messages": [{"role": "user", "content": "stream me"}],
               "max_tokens": 16}
        try:
            assert gw.predict(req, path="/v1/chat/completions",
                              timeout=60)["object"] == "chat.completion"
            result = {}

            def call():
                try:
                    result["out"] = gw.predict(
                        req, path="/v1/chat/completions", timeout=10)
                except Exception as e:  # the CLEAN surface we assert on
                    result["err"] = e

            t = threading.Thread(target=call)
            t.start()
            time.sleep(0.2)          # request is mid-stream on the victim
            rs.replicas[0].stop()    # crash
            t.join(timeout=15)
            assert not t.is_alive(), "gateway call hung past its timeout"
            assert ("err" in result) or ("out" in result
                                         and result["out"].get("object")
                                         == "chat.completion")
            # heal and resume
            assert rs.health_check() >= 1
            out = gw.predict(req, path="/v1/chat/completions", timeout=60)
            assert out["object"] == "chat.completion"
        finally:
            rs.stop()


@pytest.mark.slow
class TestConcurrencySoak:
    def test_soak_48_requests_mixed_adapters_compile_once(
            self, lora_setup, xla_compile_counter):
        args, bundle, params, tok = lora_setup
        batched = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 4, "block_size": 16, "prefill_chunk": 8,
                        "max_adapters": 8})
        batched.adapter_bank.add("siloA", _rand_adapter(params, 60))
        batched.adapter_bank.add("siloB", _rand_adapter(params, 61))
        try:
            batched.generate("warm", max_new_tokens=4)  # compile warmup
            xla_compile_counter.reset()

            def one(i):
                return batched.generate(
                    f"req {i} {'pad ' * (i % 7)}", max_new_tokens=8,
                    temperature=(0.0 if i % 3 else 1.1), seed=i,
                    adapter=[None, "siloA", "siloB"][i % 3])

            with cf.ThreadPoolExecutor(12) as ex:
                outs = list(ex.map(one, range(48)))
            assert len(outs) == 48
            assert all(o["finish_reason"] in ("stop", "length")
                       for o in outs)
            assert xla_compile_counter.delta() == 0
        finally:
            batched.close()


# -------------------------------- serving observability plane (ISSUE 10) ----

class _WedgeScheduler:
    """Duck-typed scheduler whose step() blocks until released — the
    deliberately wedged engine the watchdog/flight-recorder acceptance
    test needs, without burning a compile."""

    def __init__(self):
        from types import SimpleNamespace
        self.cfg = SimpleNamespace(max_seq_len=64)
        self.cache_cfg = SimpleNamespace(
            num_blocks=16, max_seq_len=64,
            blocks_needed=lambda n: 1)
        self.release_evt = threading.Event()
        self.last_step_finite = True
        self.steps_run = 0
        self._active = 0

    def can_admit(self, prompt_len, max_new):
        return self._active == 0

    def admit(self, ids, **kw):
        from fedml_tpu.llm.data import EOS
        self._active = 1
        return 0, EOS + 4   # slot 0, a non-EOS first token

    def release(self, slot):
        self._active = 0

    def step(self):
        self.steps_run += 1
        self.release_evt.wait(timeout=30)
        return {}

    def active_count(self):
        return self._active

    def slot_position(self, slot):
        return 5

    def kv_pool_stats(self):
        return {"used_blocks": 1, "free_blocks": 15,
                "headroom_requests": 3, "fragmentation": 0.5}

    def debug_state(self):
        return {"slots": [{"slot": 0, "active": bool(self._active)}],
                "kv_pool": self.kv_pool_stats()}


class TestServingTraces:
    def _report_mod(self):
        import os
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "scripts"))
        import serving_report
        return serving_report

    def test_e2e_traces_schema_valid_and_95pct_attributed(
            self, predictors, tmp_path):
        """The acceptance pin: an 8-concurrent-request session (one
        deadline eviction) produces schema-valid traces whose waterfalls
        attribute >=95% of each request's submit->finish wall to named
        spans, reconstructed by scripts/serving_report.py."""
        import json
        import os
        from fedml_tpu.core import mlops
        from fedml_tpu.core.obs import schema as obs_schema
        _, batched = predictors
        eng = batched.engine
        mlops.init(Arguments(log_file_dir=str(tmp_path), run_id="trc"))
        try:
            # the evictee first (so it owns a slot): long budget, short
            # leash -> deadline eviction mid-decode
            evict_fut = eng.submit(list(range(4, 22)), max_new_tokens=40,
                                   deadline_s=0.05)
            with cf.ThreadPoolExecutor(7) as ex:
                gens = [ex.submit(batched.generate,
                                  f"trace request number {i}",
                                  max_new_tokens=10)
                        for i in range(7)]
                outs = [g.result(timeout=60) for g in gens]
            evicted = evict_fut.result(timeout=60)
            time.sleep(0.3)   # let the engine close its decode_steps span
        finally:
            mlops.init(Arguments(enable_tracking=False))
        assert len(outs) == 7
        assert evicted["finish_reason"] == "deadline"
        assert evicted["completion_tokens"] < 40   # leash cut it short

        path = os.path.join(str(tmp_path), "run_trc.jsonl")
        lines = open(path).read().splitlines()
        problems = obs_schema.validate_lines(lines)
        assert not problems, problems[:10]
        spans = [json.loads(l) for l in lines
                 if json.loads(l).get("kind") == "span"]
        serving_names = {s["name"] for s in spans
                         if s["name"].startswith("serving.")}
        assert serving_names <= obs_schema.SERVING_SPAN_NAMES, \
            serving_names - obs_schema.SERVING_SPAN_NAMES
        reqs = [s for s in spans if s["name"] == "serving.request"]
        assert len(reqs) == 8
        # the evicted request's span carries the evict event
        assert any(ev["name"] == "evict"
                   for s in reqs for ev in s.get("events", []))
        # engine-side fan-in: decode_steps spans LINK the request spans
        # they advanced (the async-pour idiom)
        step_spans = [s for s in spans
                      if s["name"] == "serving.decode_steps"]
        assert step_spans
        req_ids = {s["span_id"] for s in reqs}
        linked = {ln["span_id"] for s in step_spans
                  for ln in s.get("links", [])}
        assert linked & req_ids
        # the waterfall: >=95% of every request's wall attributed
        sr = self._report_mod()
        import io
        out = io.StringIO()
        spans_l, snaps = sr.load_records([path])
        rc = sr.print_report(spans_l, snaps, None, 0.95, out=out)
        assert rc == 0, out.getvalue()
        assert "ttft_s" in out.getvalue()

    def test_http_traceparent_joins_request_trace(self, predictors,
                                                  tmp_path):
        """An inbound W3C traceparent header parents the whole serving
        lifecycle — serving.http AND the engine's serving.request land
        in the caller's trace — and the response echoes the context."""
        import json
        import os
        import urllib.request
        from fedml_tpu.core import mlops
        _, batched = predictors
        runner = ChatCompletionRunner(batched)
        port = runner.start()
        trace_id = "ab" * 16
        mlops.init(Arguments(log_file_dir=str(tmp_path), run_id="tp"))
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/chat/completions",
                data=json.dumps({
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4}).encode(),
                headers={"Content-Type": "application/json",
                         "traceparent": f"00-{trace_id}-{'cd' * 8}-01"})
            with urllib.request.urlopen(req, timeout=60) as r:
                echoed = r.headers.get("traceparent")
                assert r.status == 200
        finally:
            runner.stop()
            mlops.init(Arguments(enable_tracking=False))
        assert echoed and echoed.split("-")[1] == trace_id
        path = os.path.join(str(tmp_path), "run_tp.jsonl")
        spans = [json.loads(l) for l in open(path) if l.strip()]
        spans = [s for s in spans if s.get("kind") == "span"]
        by_name = {}
        for s in spans:
            if s["trace_id"] == trace_id:
                by_name.setdefault(s["name"], []).append(s)
        assert "serving.http" in by_name, {s["name"] for s in spans}
        assert "serving.request" in by_name
        http_sp = by_name["serving.http"][0]
        assert http_sp["parent_id"] == "cd" * 8  # the caller's span
        assert by_name["serving.request"][0]["parent_id"] \
            == http_sp["span_id"]


class TestWatchdogFlightRecorder:
    def test_wedged_engine_dumps_schema_valid_black_box(self, tmp_path):
        """The acceptance pin: a deliberately wedged engine (step blocks
        forever with occupancy > 0) trips the watchdog, and the flight-
        recorder JSONL dump validates line by line."""
        import json
        import os
        from fedml_tpu.core import mlops
        from fedml_tpu.core.obs import schema as obs_schema
        from fedml_tpu.serving.batch.engine import BatchingEngine
        mlops.init(Arguments(log_file_dir=str(tmp_path), run_id="wedge"))
        sched = _WedgeScheduler()
        eng = BatchingEngine(sched, watchdog_s=0.3, flight_records=64,
                             flight_dir=str(tmp_path))
        try:
            eng.submit([5, 6, 7], max_new_tokens=8)
            deadline = time.time() + 15.0
            while time.time() < deadline and eng.watchdog.trips == 0:
                time.sleep(0.05)
            assert eng.watchdog.trips >= 1, "watchdog never tripped"
            assert eng.watchdog.last_trip_reason == "stalled"
            assert eng.health()["status"] == "stalled"
            dump = eng._flight_path
            assert dump and os.path.exists(dump)
            lines = open(dump).read().splitlines()
            assert lines
            problems = obs_schema.validate_lines(lines)
            assert not problems, problems[:10]
            events = [json.loads(l)["event"] for l in lines]
            assert "submit" in events
            assert "admit" in events
            assert "watchdog_trip" in events
            # the trip also landed as a health record in the run log
            health = [json.loads(l) for l in open(
                os.path.join(str(tmp_path), "run_wedge.jsonl"))
                if '"health"' in l]
            health = [h for h in health if h.get("kind") == "health"]
            assert health and health[-1]["status"] == "stalled"
        finally:
            sched.release_evt.set()
            eng.stop()
            mlops.init(Arguments(enable_tracking=False))

    def test_nan_logits_trip_and_health(self, tmp_path):
        """NaN/inf in decode logits is a poisoned step: progress exists
        but the output is garbage — the watchdog must still trip."""
        from fedml_tpu.core import mlops
        from fedml_tpu.serving.batch.engine import BatchingEngine
        mlops.init(Arguments(log_file_dir=str(tmp_path), run_id="nan"))
        sched = _WedgeScheduler()
        sched.release_evt.set()   # steps return immediately
        eng = BatchingEngine(sched, watchdog_s=0.0,  # drive check() by hand
                             flight_records=16, flight_dir=str(tmp_path))
        try:
            sched.last_step_finite = False
            assert eng.health()["status"] == "nan_logits"
            assert eng.watchdog.check() == "nan_logits"
            assert eng.watchdog.trips == 1
        finally:
            eng.stop()
            mlops.init(Arguments(enable_tracking=False))

    def test_decode_step_reports_nonfinite_logits(self, lora_setup):
        """The real scheduler's poison flag: poisoned base params make
        last_step_finite go False on the very next decode step."""
        import jax.numpy as jnp
        import jax
        from fedml_tpu.serving.batch import DecodeScheduler
        args, bundle, params, tok = lora_setup
        sched = DecodeScheduler(bundle.module, bundle.cfg,
                                bundle.base_params, None,
                                slots=2, block_size=16, prefill_chunk=8)
        sched.admit([5, 6, 7], max_new_tokens=4)
        sched.step()
        assert sched.last_step_finite
        poisoned = jax.tree_util.tree_map(
            lambda l: jnp.full_like(l, jnp.nan), sched.params)
        sched.params = poisoned
        sched.step()
        assert not sched.last_step_finite


class TestLiveEndpoints:
    def test_metrics_healthz_debug_scrape_during_live_session(
            self, predictors):
        """The acceptance pin: during a live batched session, /metrics
        serves Prometheus text including the TTFT and ITL histograms;
        /healthz answers ok; /debug/state shows the slot matrix."""
        import json
        import urllib.request
        _, batched = predictors
        runner = ChatCompletionRunner(batched)
        port = runner.start()
        try:
            def post(i):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/chat/completions",
                    data=json.dumps({
                        "messages": [{"role": "user",
                                      "content": f"scrape test {i}"}],
                        "max_tokens": 24}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as r:
                    return json.load(r)

            with cf.ThreadPoolExecutor(4) as ex:
                inflight = [ex.submit(post, i) for i in range(4)]
                # scrape WHILE requests are in flight
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=10) as r:
                    assert r.status == 200
                    assert r.headers["Content-Type"].startswith(
                        "text/plain")
                    text = r.read().decode()
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz",
                        timeout=10) as r:
                    health = json.load(r)
                    assert r.status == 200
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/debug/state",
                        timeout=10) as r:
                    debug = json.load(r)
                outs = [f.result(timeout=60) for f in inflight]
            assert all(o["object"] == "chat.completion" for o in outs)
            # the SLO surface is live Prometheus text
            assert "# TYPE llm_ttft_seconds histogram" in text
            assert "llm_ttft_seconds_bucket" in text
            assert "# TYPE llm_inter_token_seconds histogram" in text
            assert "llm_inter_token_seconds_bucket" in text
            assert "llm_kv_blocks_used" in text
            assert "llm_queue_depth" in text
            assert health["status"] == "ok"
            assert "steps_run" in health
            slots = debug["scheduler"]["slots"]
            assert len(slots) == 4   # the fixture's slot matrix
            assert "kv_pool" in debug["scheduler"]
            assert "depth" in debug["queue"]
        finally:
            runner.stop()

    def test_healthz_503_when_wedged(self, tmp_path):
        import json
        import urllib.error
        import urllib.request
        from fedml_tpu.serving import FedMLInferenceRunner
        from fedml_tpu.serving.batch.engine import BatchingEngine

        class _P:
            def __init__(self, eng):
                self.eng = eng

            def predict(self, request):
                return {}

            def ready(self):
                return True

            def health(self):
                return self.eng.health()

            def debug_state(self):
                return self.eng.debug_state()

        sched = _WedgeScheduler()
        eng = BatchingEngine(sched, watchdog_s=0.2,
                             flight_dir=str(tmp_path))
        runner = FedMLInferenceRunner(_P(eng))
        port = runner.start()
        try:
            eng.submit([5, 6], max_new_tokens=4)
            deadline = time.time() + 15.0
            while time.time() < deadline and eng.watchdog.trips == 0:
                time.sleep(0.05)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10)
            assert ei.value.code == 503
            assert json.load(ei.value)["status"] == "stalled"
        finally:
            sched.release_evt.set()
            runner.stop()
            eng.stop()


class TestServingOverheadGate:
    def test_tracing_metrics_on_within_three_percent_c8(
            self, predictors, tmp_path):
        """The CI gate the ISSUE pins: batched tokens/s with tracing +
        metrics ON within 3% of OFF on the concurrency-8 block. One
        engine serves both modes (hooks read process config at call
        time), trials alternate to cancel drift, min-of-N compared with
        a 50 ms scheduler-noise floor."""
        from fedml_tpu.core import mlops
        _, batched = predictors

        def block():
            with cf.ThreadPoolExecutor(8) as ex:
                futs = [ex.submit(batched.generate,
                                  f"overhead gate req {i}",
                                  max_new_tokens=24)
                        for i in range(8)]
                outs = [f.result(timeout=120) for f in futs]
            assert all(o["completion_tokens"] > 0 for o in outs)

        on_args = Arguments(log_file_dir=str(tmp_path), run_id="s_ovh")
        off_args = Arguments(enable_tracking=False, obs_tracing=False,
                             obs_metrics=False)
        try:
            mlops.init(on_args)
            block()                     # warmup both modes
            mlops.init(off_args)
            block()
            on_t, off_t = [], []
            for _ in range(6):
                mlops.init(off_args)
                t0 = time.perf_counter()
                block()
                off_t.append(time.perf_counter() - t0)
                mlops.init(on_args)
                t0 = time.perf_counter()
                block()
                on_t.append(time.perf_counter() - t0)
        finally:
            mlops.init(Arguments(enable_tracking=False))
        best_on, best_off = min(on_t), min(off_t)
        assert best_on <= best_off * 1.03 + 0.05, (
            f"tracing+metrics cost {best_on:.4f}s vs {best_off:.4f}s "
            f"(> 3% + 50ms) at c8: on={on_t} off={off_t}")
