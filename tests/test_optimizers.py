"""Optimizer-zoo parity: every federated optimizer must produce numerically
matching results on the SP golden loop and the TPU mesh backend (SURVEY §4 —
"same algorithm, multiple backends" as a first-class test), and must learn."""

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.optimizers import available_optimizers

pytestmark = __import__('pytest').mark.slow

OPTIMIZERS = ["FedAvg", "FedProx", "FedOpt", "FedSGD", "FedLocalSGD",
              "SCAFFOLD", "FedNova", "FedDyn", "Mime"]


def make_args(**kw):
    base = dict(
        dataset="synthetic_mnist", model="lr",
        client_num_in_total=8, client_num_per_round=8,
        comm_round=2, epochs=1, batch_size=32, learning_rate=0.1,
        frequency_of_the_test=2, random_seed=7,
    )
    base.update(kw)
    return Arguments(**base)


def test_registry_has_all():
    known = available_optimizers()
    for name in OPTIMIZERS:
        assert name.lower() in known, (name, known)


@pytest.mark.parametrize("opt_name", OPTIMIZERS)
def test_sp_tpu_parity(opt_name):
    kw = dict(federated_optimizer=opt_name)
    if opt_name in ("SCAFFOLD", "FedDyn"):
        kw["learning_rate"] = 0.05
    r_sp = fedml_tpu.run_simulation(backend="sp", args=make_args(**kw))
    r_tpu = fedml_tpu.run_simulation(backend="tpu", args=make_args(**kw))
    for a, b in zip(jax.tree_util.tree_leaves(r_sp["params"]),
                    jax.tree_util.tree_leaves(r_tpu["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.parametrize("opt_name", ["FedProx", "FedOpt", "SCAFFOLD",
                                      "FedNova", "FedDyn", "Mime"])
def test_learns(opt_name):
    args = make_args(federated_optimizer=opt_name, comm_round=8,
                     learning_rate=0.05 if opt_name in ("SCAFFOLD", "FedDyn")
                     else 0.1)
    result = fedml_tpu.run_simulation(backend="tpu", args=args)
    assert result["final_test_acc"] > 0.5, result["history"][-1]


def test_stateful_partial_participation_parity():
    """Client state (SCAFFOLD c_i) must persist correctly when only some
    clients participate each round — exercises the masked state-update path
    in the TPU engine."""
    kw = dict(federated_optimizer="SCAFFOLD", client_num_in_total=16,
              client_num_per_round=6, comm_round=3, learning_rate=0.05)
    r_sp = fedml_tpu.run_simulation(backend="sp", args=make_args(**kw))
    r_tpu = fedml_tpu.run_simulation(backend="tpu", args=make_args(**kw))
    for a, b in zip(jax.tree_util.tree_leaves(r_sp["params"]),
                    jax.tree_util.tree_leaves(r_tpu["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
