"""MPC: finite-field ops (vs python bignum ground truth), quantization
round-trip, Shamir sharing, full SecAgg protocol with dropout, LightSecAgg
one-shot reconstruction."""

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.mpc import (P, SecAggClient, aggregate_encoded,
                                decode_aggregate_mask, dequantize, expand_mask,
                                ff_add, ff_mul, ff_sub, mask_encoding,
                                pairwise_seed, quantize, secagg_unmask,
                                shamir_reconstruct, shamir_share, sum_mod_p)

_P = int(P)


class TestFieldOps:
    def test_add_sub_vs_bignum(self):
        rng = np.random.RandomState(0)
        a = rng.randint(0, _P, 1000).astype(np.uint32)
        b = rng.randint(0, _P, 1000).astype(np.uint32)
        got = np.asarray(ff_add(jnp.asarray(a), jnp.asarray(b)))
        want = (a.astype(object) + b.astype(object)) % _P
        np.testing.assert_array_equal(got.astype(object), want)
        got = np.asarray(ff_sub(jnp.asarray(a), jnp.asarray(b)))
        want = (a.astype(object) - b.astype(object)) % _P
        np.testing.assert_array_equal(got.astype(object), want)

    def test_mul_vs_bignum(self):
        rng = np.random.RandomState(1)
        # include edge values
        edge = np.asarray([0, 1, 2, _P - 1, _P - 2, 2**16, 2**16 - 1,
                           2**30, 2**30 + 1], np.uint32)
        a = np.concatenate([edge, rng.randint(0, _P, 2000).astype(np.uint32)])
        b = np.concatenate([edge[::-1], rng.randint(0, _P, 2000).astype(np.uint32)])
        got = np.asarray(ff_mul(jnp.asarray(a), jnp.asarray(b)))
        want = (a.astype(object) * b.astype(object)) % _P
        np.testing.assert_array_equal(got.astype(object), want)

    def test_quantize_roundtrip(self):
        x = jnp.asarray(np.random.RandomState(2).randn(1000).astype(np.float32))
        q = quantize(x)
        back = dequantize(q)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=2e-5)

    def test_sum_mod_p_matches_bignum(self):
        rng = np.random.RandomState(3)
        m = rng.randint(0, _P, size=(50, 200)).astype(np.uint32)
        got = np.asarray(sum_mod_p(jnp.asarray(m)))
        want = np.sum(m.astype(object), axis=0) % _P
        np.testing.assert_array_equal(got.astype(object), want)


class TestShamir:
    def test_share_reconstruct(self):
        rng = np.random.RandomState(0)
        secret = 123456789
        shares = shamir_share(secret, n_shares=7, threshold=4, rng=rng)
        assert shamir_reconstruct(shares[:4]) == secret
        assert shamir_reconstruct(shares[3:]) == secret  # any 4 work

    def test_below_threshold_wrong(self):
        rng = np.random.RandomState(0)
        shares = shamir_share(42, n_shares=5, threshold=3, rng=rng)
        assert shamir_reconstruct(shares[:2]) != 42  # w.h.p.


class TestSecAggProtocol:
    def _run(self, n=5, t=3, drop=()):
        d = 64
        rng = np.random.RandomState(0)
        vecs = [rng.randn(d).astype(np.float32) * 0.5 for _ in range(n)]
        clients = [SecAggClient(i, n, t, seed=100 + i) for i in range(n)]
        publics = {c.cid: c.public_key for c in clients}
        for c in clients:
            c.receive_publics(publics)
        # round 2: everyone shares seeds/keys; server stores per-owner shares
        seed_shares = {i: [] for i in range(n)}
        key_shares = {i: [] for i in range(n)}
        for c in clients:
            sh = c.make_shares()
            for j, (ss, ks) in sh.items():
                seed_shares[c.cid].append(ss)
                key_shares[c.cid].append(ks)
        surviving = [i for i in range(n) if i not in drop]
        masked = {i: clients[i].masked_update(vecs[i]) for i in surviving}
        masked_sum = np.zeros(d, np.uint64)
        for m in masked.values():
            masked_sum = (masked_sum + m) % _P
        unmasked = secagg_unmask(
            masked_sum.astype(np.uint32), surviving, list(drop),
            {i: seed_shares[i][:t] for i in surviving},
            {i: key_shares[i][:t] for i in drop},
            publics, d)
        got = np.asarray(dequantize(jnp.asarray(unmasked)))
        want = np.sum([vecs[i] for i in surviving], axis=0)
        np.testing.assert_allclose(got, want, atol=1e-3)

    def test_no_dropout(self):
        self._run()

    def test_one_dropout(self):
        self._run(drop=(2,))

    def test_two_dropouts(self):
        self._run(n=6, t=3, drop=(1, 4))

    def test_mask_hides_input(self):
        """A single masked update must look uniform — no correlation with
        the plaintext quantization."""
        n, t, d = 4, 2, 256
        clients = [SecAggClient(i, n, t, seed=7 + i) for i in range(n)]
        publics = {c.cid: c.public_key for c in clients}
        for c in clients:
            c.receive_publics(publics)
        vec = np.ones(d, np.float32)
        masked = clients[0].masked_update(vec)
        q = np.asarray(quantize(jnp.asarray(vec)))
        diffs = (masked.astype(np.int64) - q.astype(np.int64)) % _P
        # the mask should spread over the field, not cluster near 0
        assert np.std(diffs.astype(np.float64)) > _P / 10


class TestLightSecAgg:
    def test_aggregate_mask_reconstruction(self):
        n, t_priv, t_split, d = 6, 2, 2, 32
        rng = np.random.RandomState(0)
        masks = [rng.randint(0, _P, d).astype(np.uint64) for _ in range(n)]
        # each client encodes its mask; client j holds the j-th coded row
        coded = [mask_encoding(masks[i], n, t_priv, t_split,
                               np.random.RandomState(50 + i))
                 for i in range(n)]
        # client 3 drops before sending its masked model: surviving clients
        # sum the coded sub-masks of the surviving owners only
        surviving = [0, 1, 2, 4, 5]
        responses = [aggregate_encoded([coded[i][j] for i in surviving])
                     for j in surviving]
        agg_mask = decode_aggregate_mask(
            responses, surviving, n, t_priv, t_split, d)
        want = np.zeros(d, np.uint64)
        for i in surviving:
            want = (want + masks[i]) % _P
        np.testing.assert_array_equal(agg_mask % _P, want)
