"""Remote log shipping (VERDICT r3 item 8 / inventory row 65): the runtime
log daemon tails per-run files and POSTs batches to an HTTP log server —
with retry on transient failures and rotation awareness — completing the
remote half of observability (reference mlops_runtime_log_daemon.py)."""

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from fedml_tpu.core.mlops.log_daemon import LogShipper


class _Collector(BaseHTTPRequestHandler):
    fail_next = 0
    received = []

    def do_POST(self):
        body = self.rfile.read(int(self.headers["Content-Length"]))
        if _Collector.fail_next > 0:
            _Collector.fail_next -= 1
            self.send_response(500)
            self.end_headers()
            return
        _Collector.received.append(json.loads(body))
        self.send_response(200)
        self.end_headers()

    def log_message(self, *a):
        pass


@pytest.fixture()
def log_server():
    _Collector.received = []
    _Collector.fail_next = 0
    srv = HTTPServer(("127.0.0.1", 0), _Collector)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}/logs", _Collector
    srv.shutdown()


def test_batching_and_metadata(tmp_path, log_server):
    url, col = log_server
    path = str(tmp_path / "job.log")
    with open(path, "w") as f:
        for i in range(250):
            f.write(f"line {i}\n")
    s = LogShipper(path, url, run_id="r1", device_id="7", batch_lines=100)
    shipped = s.pump_once()
    assert shipped == 250
    assert [len(b["log_lines"]) for b in col.received] == [100, 100, 50]
    assert col.received[0]["run_id"] == "r1"
    assert col.received[0]["device_id"] == "7"
    assert [b["seq"] for b in col.received] == [0, 1, 2]
    # nothing new -> nothing shipped
    assert s.pump_once() == 0
    # appended lines ship incrementally; a partial line waits for its \n
    with open(path, "a") as f:
        f.write("more A\nmore B\npartial")
    assert s.pump_once() == 2
    with open(path, "a") as f:
        f.write(" done\n")
    assert s.pump_once() == 1
    assert col.received[-1]["log_lines"] == ["partial done"]


def test_retry_on_transient_failure(tmp_path, log_server):
    url, col = log_server
    path = str(tmp_path / "job.log")
    with open(path, "w") as f:
        f.write("hello\n")
    col.fail_next = 2  # two 500s, then healthy
    s = LogShipper(path, url, retries=4)
    assert s.pump_once() == 1
    assert s.failed_batches == 0
    assert col.received[-1]["log_lines"] == ["hello"]


def test_rotation_awareness(tmp_path, log_server):
    url, col = log_server
    path = str(tmp_path / "job.log")
    with open(path, "w") as f:
        f.write("old 1\nold 2\n")
    s = LogShipper(path, url)
    assert s.pump_once() == 2
    # rotate: move the old file away, create a fresh one at the same path
    os.replace(path, str(tmp_path / "job.log.1"))
    with open(path, "w") as f:
        f.write("new 1\n")
    assert s.pump_once() == 1
    assert col.received[-1]["log_lines"] == ["new 1"]
    # truncation (copytruncate-style rotation) also re-tails. Detection is
    # size-based, so the shrunken file must actually be shorter than the
    # old offset — an equal-size rewrite is indistinguishable by stat.
    with open(path, "w") as f:
        f.write("hi\n")
    assert s.pump_once() == 1
    assert col.received[-1]["log_lines"] == ["hi"]


def test_background_thread_ships_and_flushes_on_stop(tmp_path, log_server):
    url, col = log_server
    path = str(tmp_path / "job.log")
    with open(path, "w") as f:
        f.write("a\n")
    s = LogShipper(path, url, interval_s=0.05).start()
    deadline = time.time() + 5
    while time.time() < deadline and s.shipped_lines < 1:
        time.sleep(0.05)
    assert s.shipped_lines == 1
    with open(path, "a") as f:
        f.write("b\n")
    s.stop()  # final flush must pick up 'b'
    assert s.shipped_lines == 2


def test_wired_into_mlops_init(tmp_path, log_server, monkeypatch):
    url, col = log_server
    from fedml_tpu.core import mlops
    from fedml_tpu.core.mlops import log_daemon
    from fedml_tpu.arguments import Arguments

    args = Arguments(dataset="digits", model="lr", run_id="ship1",
                     log_file_dir=str(tmp_path), log_server_url=url)
    mlops.init(args)
    mlops.log({"acc": 0.5}, step=0)
    for s in log_daemon._shippers:
        s.pump_once()
    log_daemon.stop_all_shippers()
    mine = [b for b in col.received if b["run_id"] == "ship1"]
    assert mine and any("acc" in ln for b in mine for ln in b["log_lines"])


def test_cr_and_crlf_and_binary_lines(tmp_path, log_server):
    """Binary tailing must keep universal newlines: \r-only progress bars
    (tqdm-style) and CRLF logs still split into lines, and non-UTF-8
    bytes neither crash nor desync the byte-offset bookkeeping."""
    url, col = log_server
    path = str(tmp_path / "job.log")
    with open(path, "wb") as f:
        f.write(b"epoch 1/3\repoch 2/3\repoch 3/3\r\n")
        f.write(b"crlf line\r\n")
        f.write(b"raw \xff\xfe bytes\n")
    s = LogShipper(path, url)
    assert s.pump_once() == 5
    lines = [ln for b in col.received for ln in b["log_lines"]]
    assert lines[:3] == ["epoch 1/3", "epoch 2/3", "epoch 3/3"]
    assert lines[3] == "crlf line"          # no trailing \r shipped
    assert "raw" in lines[4] and "bytes" in lines[4]
    # byte offset equals the true file size even with non-UTF-8 content
    assert s._offset == os.path.getsize(path)
    # a \r-terminated tail is a complete line, not hoarded in the buffer
    with open(path, "ab") as f:
        f.write(b"progress 10%\r")
    assert s.pump_once() == 1


def test_stop_flushes_tail_before_first_poll_interval(tmp_path,
                                                      log_server):
    """The short-run satellite fix: a run that finishes inside the first
    poll interval must not lose its tail — stop() guarantees the final
    flush even when the loop thread never completed a cycle (and even
    when it was never started at all)."""
    url, col = log_server
    path = str(tmp_path / "job.log")
    with open(path, "w") as f:
        f.write("only line\npartial tail")
    # long interval: the loop thread will NOT have pumped before stop
    s = LogShipper(path, url, interval_s=60.0).start()
    s.stop()
    lines = [ln for b in col.received for ln in b["log_lines"]]
    # the complete line AND the newline-less tail both shipped
    assert "only line" in lines and "partial tail" in lines
    # never-started shipper: stop() still flushes
    with open(path, "a") as f:
        f.write(" grew\nfresh\n")
    col.received.clear()
    s2 = LogShipper(path, url)
    s2.stop()
    lines = [ln for b in col.received for ln in b["log_lines"]]
    assert "fresh" in lines


def test_final_flush_runs_exactly_once(tmp_path, log_server):
    """stop() after the loop thread already flushed (and the atexit hook
    after stop()) must not re-ship the tail — the flush is deduped."""
    url, col = log_server
    path = str(tmp_path / "job.log")
    with open(path, "w") as f:
        f.write("tail with no newline")
    s = LogShipper(path, url, interval_s=0.05).start()
    s.stop()          # loop thread flushes on the stop event; dedup here
    s.stop()          # second stop: no double flush
    s._atexit_stop()  # simulated interpreter exit after stop: no-op
    lines = [ln for b in col.received for ln in b["log_lines"]]
    assert lines.count("tail with no newline") == 1


def test_atexit_hook_registered_and_unregistered(tmp_path, log_server):
    """start() registers the interpreter-exit flush; stop() retires it
    so a long-lived process doesn't accumulate dead hooks."""
    import atexit
    url, _ = log_server
    path = str(tmp_path / "job.log")
    open(path, "w").write("x\n")
    registered = []
    real_register = atexit.register
    real_unregister = atexit.unregister
    try:
        atexit.register = lambda fn, *a, **k: registered.append(fn)
        atexit.unregister = lambda fn: registered.remove(fn)
        s = LogShipper(path, url, interval_s=60.0).start()
        assert registered and registered[0].__name__ == "_atexit_stop"
        s.stop()
        assert not registered
    finally:
        atexit.register = real_register
        atexit.unregister = real_unregister
