"""Full cross-silo FL sessions across OS processes over real gRPC sockets
(VERDICT r3 item 4): server + 3 clients as separate interpreters, for both
the plain FedAvg FSM and the SecAgg secure-aggregation runtime (reference
``tests/cross-silo/run_cross_silo.sh:10-18``)."""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "grpc_session_worker.py")
N_CLIENTS = 3


def _free_port_block(n: int = 8) -> int:
    """A base port whose +0..+n block is free (ranks listen on base+rank)."""
    while True:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            base = s.getsockname()[1]
        if base + n < 65535 and all(_port_free(base + i)
                                    for i in range(1, n)):
            return base


def _port_free(port: int) -> bool:
    with socket.socket() as s:
        try:
            s.bind(("127.0.0.1", port))
            return True
        except OSError:
            return False


def _wait_listening(port: int, timeout_s: float = 60.0) -> None:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=1.0):
                return
        except OSError:
            time.sleep(0.2)
    raise TimeoutError(f"port {port} never came up")


def _run_session(optimizer: str, tmp_path) -> dict:
    base = _free_port_block()
    out_path = str(tmp_path / f"result_{optimizer}.json")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"

    def spawn(role, rank):
        return subprocess.Popen(
            [sys.executable, WORKER, role, str(rank), str(base),
             optimizer, out_path], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    procs = [spawn("server", 0)]
    try:
        _wait_listening(base)  # server's gRPC listener before client sends
        procs += [spawn("client", r) for r in range(1, N_CLIENTS + 1)]
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail(f"gRPC {optimizer} session timed out")
            outs.append(out.decode(errors="replace"))
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    with open(out_path) as f:
        return json.load(f)


def test_grpc_multiprocess_fedavg_session(tmp_path):
    res = _run_session("FedAvg", tmp_path)
    assert res["error"] is None
    assert res["rounds"] == 2
    assert res["final_test_acc"] is not None and res["final_test_acc"] > 0.3


def test_grpc_multiprocess_secagg_session(tmp_path):
    """The SecAgg runtime's full per-round protocol (channel keys, fresh
    round keys, sealed Shamir shares, masked models, unmask) across real
    process boundaries and real sockets."""
    res = _run_session("secagg", tmp_path)
    assert res["error"] is None
    assert res["rounds"] == 2
    assert res["final_test_acc"] is not None and res["final_test_acc"] > 0.3


def test_grpc_multiprocess_splitnn_session(tmp_path):
    """Split learning as a real multi-process protocol (VERDICT r4 item
    1): cut-layer activations stream client->server and activation
    gradients stream back over gRPC, clients trained round-robin."""
    res = _run_session("split_nn", tmp_path)
    assert res["error"] is None
    assert res["rounds"] == 2
    assert res["final_test_acc"] is not None and res["final_test_acc"] > 0.3


def test_grpc_multiprocess_vfl_session(tmp_path):
    """Vertical FL as a real multi-process protocol: three feature
    parties send logit contributions, the label-party server returns
    d(loss)/d(logits), over gRPC."""
    res = _run_session("vfl", tmp_path)
    assert res["error"] is None
    assert res["rounds"] == 2
    assert res["final_test_acc"] is not None and res["final_test_acc"] > 0.3


def test_grpc_multiprocess_gossip_session(tmp_path):
    """Decentralized FL with NO server: four OS processes gossip
    parameters with topology neighbors over gRPC (VERDICT r4 item 4);
    rank 0 reports the avg-model accuracy."""
    res = _run_session("decentralized_fl", tmp_path)
    assert res["error"] is None
    assert res["rounds"] == 2
    assert res["final_test_acc"] is not None and res["final_test_acc"] > 0.3
