"""Worker for test_grpc_multiprocess_session: one role (server or client
rank) of a cross-silo FL session over real gRPC sockets, driven through
the public ``CrossSiloRunner`` dispatch — including the SecAgg federated
optimizer, whose whole message FSM (channel keys -> round keys -> shares
-> masked models -> unmask) rides the same transport.

Usage: grpc_session_worker.py <role> <rank> <base_port> <optimizer> <out>
"""

import json
import os
import sys


def main() -> None:
    role, rank, base_port, optimizer, out_path = sys.argv[1:6]
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from fedml_tpu import data as data_mod, model as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.cross_silo.horizontal.runner import CrossSiloRunner

    # decentralized gossip has no server: all 4 processes are nodes
    n_total = 4 if optimizer in ("decentralized_fl", "gossip") else 3
    args = Arguments(
        dataset="digits", model="lr", client_num_in_total=n_total,
        client_num_per_round=3, party_num=3, comm_round=2, epochs=1,
        batch_size=32, learning_rate=0.1, random_seed=11,
        training_type="cross_silo", federated_optimizer=optimizer,
        backend="GRPC", grpc_base_port=int(base_port), role=role,
        rank=int(rank), round_timeout_s=30.0)
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    runner = CrossSiloRunner(args, fed, bundle)
    result = runner.run()

    if role == "server":
        out = {"error": None, "rounds": None, "final_test_acc": None}
        if isinstance(result, dict):
            out["error"] = result.get("error")
            out["final_test_acc"] = result.get("final_test_acc")
            hist = result.get("history") or []
            out["rounds"] = len(hist)
        with open(out_path, "w") as f:
            json.dump(out, f)


if __name__ == "__main__":
    main()
