"""Federated analytics: each analyzer/aggregator pair against ground truth
computed on the pooled data."""

import numpy as np

from fedml_tpu import fa


class A:
    comm_round = 1
    client_num_per_round = 4


def client_values(seed=0, k=4, n=200):
    rng = np.random.RandomState(seed)
    return [rng.randn(n) * (i + 1) for i in range(k)]


def test_avg():
    datas = client_values()
    out = fa.run_fa("avg", datas, A())
    pooled = np.concatenate(datas)
    assert abs(out["result"] - pooled.mean()) < 1e-9


def test_frequency():
    datas = [[1, 1, 2], [2, 3], [3, 3, 3]]
    out = fa.run_fa("frequency_estimation", datas, A())
    assert out["result"][1] == 2 and out["result"][3] == 4


def test_intersection_and_union():
    datas = [{1, 2, 3}, {2, 3, 4}, {2, 3, 9}, {0, 2, 3}]
    out = fa.run_fa("intersection", [list(d) for d in datas], A())
    assert out["result"] == {2, 3}
    out = fa.run_fa("union", [list(d) for d in datas], A())
    assert out["result"] == {0, 1, 2, 3, 4, 9}


def test_k_percentile_bisection_converges():
    rng = np.random.RandomState(0)
    datas = [rng.uniform(0, 100, 500) for _ in range(4)]
    args = A()
    args.comm_round = 40
    args.k_percentile = 50
    out = fa.run_fa("k_percentile", datas, args,
                    comm_round=40)
    pooled = np.concatenate(datas)
    assert abs(out["result"] - np.median(pooled)) < 2.0


def test_triehh_finds_heavy_hitters():
    # 7 clients; three hold only "the", three only "cat" (votes are then
    # deterministic), one holds the rare "zebra"
    datas = [["the"]] * 3 + [["cat"]] * 3 + [["zebra"]]
    args = A()
    args.client_num_per_round = 7
    args.triehh_theta = 3
    out = fa.run_fa("heavy_hitter_triehh", datas, args, comm_round=8)
    found = set(out["result"])
    assert "the" in found and "cat" in found
    assert "zebra" not in found


def test_fa_cross_silo_session_matches_sim():
    """FA over the WAN FSM (reference fa/cross_silo/): the session's
    aggregate equals the in-process simulator's on the same shards."""
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.fa.analyzers import AvgAggregator, AvgClientAnalyzer
    from fedml_tpu.fa.cross_silo import run_fa_cross_silo_inproc

    datas = client_values()
    args = Arguments(comm_round=1, client_num_per_round=4,
                     training_type="cross_silo")
    out = run_fa_cross_silo_inproc(args, datas,
                                   analyzer_factory=AvgClientAnalyzer,
                                   aggregator=AvgAggregator())
    pooled = np.concatenate(datas)
    assert abs(out["result"] - pooled.mean()) < 1e-9
    assert out["rounds"] == 1


def test_fa_server_dedups_and_drops_stale_rounds():
    """Duplicate submissions (client retry) count once; submissions tagged
    with a stale round index are dropped (ADVICE r2)."""
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.distributed.communication.message import Message
    from fedml_tpu.fa.cross_silo import FAMessage, FAServerManager

    folded = []

    class Agg:
        def get_init_msg(self):
            return None

        def aggregate(self, subs):
            folded.append(list(subs))
            return sum(subs)

        def get_server_data(self):
            return folded

    from fedml_tpu.core.distributed.communication.inproc import InProcBroker

    args = Arguments(comm_round=2, training_type="fa",
                     inproc_broker=InProcBroker())
    srv = FAServerManager(args, Agg(), rank=0, size=3, backend="INPROC")
    srv.send_message = lambda msg: None  # no transport in this unit test
    srv.finish = lambda: None

    def sub(sender, value, round_idx):
        m = Message(FAMessage.C2S_SUBMISSION, sender, 0)
        m.add_params(FAMessage.KEY_SUBMISSION, value)
        m.add_params(FAMessage.KEY_ROUND, round_idx)
        return m

    srv.on_submission(sub(1, 10, 0))
    srv.on_submission(sub(1, 10, 0))     # retry: must not close the round
    assert srv.round_idx == 0 and not folded
    srv.on_submission(sub(2, 99, 5))     # wrong round: dropped
    assert srv.round_idx == 0 and not folded
    srv.on_submission(sub(2, 5, 0))      # second distinct sender closes it
    assert srv.round_idx == 1
    assert folded == [[10, 5]]
    srv.on_submission(sub(1, 1, 0))      # late round-0 dupe: dropped
    assert srv.round_idx == 1 and len(folded) == 1
