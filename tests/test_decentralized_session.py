"""Decentralized (gossip) FL over a real transport (VERDICT r4 item 4):
nodes exchange parameters with topology neighbors as Messages, with
parity against the fused SP simulator on the same config."""

import numpy as np
import pytest

from fedml_tpu import data as data_mod
from fedml_tpu import model as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_silo.decentralized import run_gossip_inproc
from fedml_tpu.runner import FedMLRunner

pytestmark = pytest.mark.slow


def _args(**kw):
    base = dict(dataset="digits", model="lr", client_num_in_total=4,
                client_num_per_round=4, comm_round=4, epochs=1,
                batch_size=32, learning_rate=0.1,
                frequency_of_the_test=1, random_seed=5,
                federated_optimizer="decentralized_fl",
                topology_neighbors=2)
    base.update(kw)
    return Arguments(**base)


def test_gossip_session_matches_sp_simulator():
    """Same topology matrix, same local steps, same mixing — the message
    protocol and the fused einsum round are the same trajectory."""
    args = _args(training_type="cross_silo")
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    dist = run_gossip_inproc(args, fed, bundle)
    sp_args = _args(training_type="simulation")
    sp = FedMLRunner(sp_args, dataset=fed, model=bundle).run()
    assert dist is not None
    assert dist["rounds"] == sp["rounds"] == 4
    assert abs(dist["final_test_acc"] - sp["final_test_acc"]) < 0.02
    assert abs(dist["consensus_dist"]
               - sp["history"][-1]["consensus_dist"]) < 1e-2
    assert dist["final_test_acc"] > 0.5
    # gossip actually mixed: nodes are closer than untrained divergence
    assert dist["consensus_dist"] < 1.0


def test_gossip_node_neighbor_sets_are_consistent():
    """Every directed edge a node expects to receive on is an edge some
    neighbor sends on (symmetric topology => identical in/out sets)."""
    from fedml_tpu.cross_silo.decentralized import GossipNodeManager
    from fedml_tpu.core.distributed.communication.inproc import InProcBroker
    args = _args(training_type="cross_silo")
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    args.inproc_broker = InProcBroker()
    nodes = [GossipNodeManager(args, fed, bundle, rank=r, size=4,
                               backend="INPROC") for r in range(4)]
    for nd in nodes:
        for j in nd.neighbors:
            assert nd.rank in nodes[j].neighbors
    # row-stochastic weights
    for nd in nodes:
        np.testing.assert_allclose(nd.W.sum(axis=1), 1.0, atol=1e-9)
