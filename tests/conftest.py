"""Test harness: force an 8-device virtual CPU platform BEFORE any test
imports jax, so every test can exercise real multi-chip sharding semantics
without TPU hardware (SURVEY §4: parity tests run on
``--xla_force_host_platform_device_count``).

Note: the environment pins ``JAX_PLATFORMS`` to the TPU tunnel and the env
var alone does not win — ``jax.config.update`` does.
"""

import os
import subprocess
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "collective_call_terminate" not in flags:
    # XLA:CPU kills the process when a collective waits >40 s for a slow
    # peer. On the virtual 8-device mesh a conv-heavy example (resnet18)
    # legitimately keeps busy devices computing for minutes while padded
    # devices idle at the all-reduce — raise the limits; slowness on a
    # TEST mesh is not an error condition.
    #
    # These flags are version-dependent, and XLA ABORTS the process on an
    # unknown flag at first backend init (parse_flags_from_env.cc) — which
    # would kill the whole pytest run. Probe support in a throwaway
    # subprocess and only keep them if that survives; support is a pure
    # function of the installed jaxlib, so cache the verdict per version.
    candidate = (flags
                 + " --xla_cpu_collective_call_warn_stuck_timeout_seconds=300"
                 + " --xla_cpu_collective_call_terminate_timeout_seconds=1200")
    import hashlib
    import tempfile
    # key the verdict on the EXACT candidate string, not just the jaxlib
    # version: pre-existing env XLA_FLAGS are embedded in the candidate, so
    # a verdict from one environment must not be reused in another
    try:  # no dist metadata for conda/source/vendored jaxlib builds —
        # the hash of the candidate still keys the cache, just coarser
        import importlib.metadata
        jaxlib_ver = importlib.metadata.version("jaxlib")
    except Exception:
        jaxlib_ver = "unknown"
    cand_key = hashlib.sha256(candidate.encode()).hexdigest()[:12]
    cache = os.path.join(
        tempfile.gettempdir(),
        f"fedml_tpu_xla_flag_probe_{jaxlib_ver}_{cand_key}")
    try:
        verdict = open(cache).read().strip()
    except OSError:
        cacheable = True
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                env={**os.environ, "XLA_FLAGS": candidate},
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                timeout=120)
            verdict = "ok" if probe.returncode == 0 else "bad"
            if probe.returncode < 0:
                # killed by a signal (OOM/SIGKILL): environment trouble,
                # not a flag verdict — don't cache it
                cacheable = False
        except subprocess.TimeoutExpired:
            # a loaded host, not a flag verdict: skip the flags this run
            # but don't poison the cache with a permanent 'bad'
            verdict, cacheable = "bad", False
        if cacheable:
            try:
                with open(cache, "w") as f:
                    f.write(verdict)
            except OSError:
                pass  # unwritable tmp: just probe again next run
    if verdict == "ok":
        flags = candidate
os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


class _CompileDelta(int):
    """An int whose repr carries the latest recompile-forensics records:
    a failing ``assert delta() == 0`` then NAMES the program and the
    changed abstract shapes instead of printing a bare counter."""

    def __repr__(self):  # pytest shows repr() of compared operands
        n = int(self)
        if n == 0:
            return str(n)
        from fedml_tpu.core.obs import roofline
        recs = roofline.recent_recompiles()
        if not recs:
            return (f"{n} (no recompile-forensics record — the compile "
                    "came from a seam outside the dispatch trackers)")
        det = "; ".join(
            f"{r['program']}: " + (", ".join(
                f"{c['arg']} {c['was']} -> {c['now']}"
                for c in (r.get("changed") or [])[:4])
                or (r.get("note") or "?"))
            for r in recs[-3:])
        return f"{n} (recompile forensics: {det})"


@pytest.fixture
def xla_compile_counter():
    """Counts XLA backend compiles via the process-wide jax.monitoring
    listener at the mlops seam. Use ``reset()`` after warmup, then assert
    ``delta() == 0`` across steady-state work — a nonzero delta is a
    shape-instability regression that would otherwise recompile silently
    every round. On failure the delta's repr prints the recompile
    forensics (core/obs/roofline), naming the shapes that moved."""
    from fedml_tpu.core import mlops

    mlops.install_compile_counter()

    class _Counter:
        def __init__(self):
            self._start = mlops.compile_count()

        def reset(self):
            self._start = mlops.compile_count()

        def delta(self):
            return _CompileDelta(mlops.compile_count() - self._start)

    return _Counter()
