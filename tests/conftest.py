"""Test harness: force an 8-device virtual CPU platform BEFORE any test
imports jax, so every test can exercise real multi-chip sharding semantics
without TPU hardware (SURVEY §4: parity tests run on
``--xla_force_host_platform_device_count``).

Note: the environment pins ``JAX_PLATFORMS`` to the TPU tunnel and the env
var alone does not win — ``jax.config.update`` does.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
