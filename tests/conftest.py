"""Test harness: force an 8-device virtual CPU platform BEFORE any test
imports jax, so every test can exercise real multi-chip sharding semantics
without TPU hardware (SURVEY §4: parity tests run on
``--xla_force_host_platform_device_count``).

Note: the environment pins ``JAX_PLATFORMS`` to the TPU tunnel and the env
var alone does not win — ``jax.config.update`` does.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "collective_call_terminate" not in flags:
    # XLA:CPU kills the process when a collective waits >40 s for a slow
    # peer. On the virtual 8-device mesh a conv-heavy example (resnet18)
    # legitimately keeps busy devices computing for minutes while padded
    # devices idle at the all-reduce — raise the limits; slowness on a
    # TEST mesh is not an error condition.
    flags += (" --xla_cpu_collective_call_warn_stuck_timeout_seconds=300"
              " --xla_cpu_collective_call_terminate_timeout_seconds=1200")
os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
