"""Quantized ``all_to_all`` re-layout on the fused robust path (ISSUE 16
tentpole part 3).

The [S, D] -> [S*n, D/n] re-layout carries (g-1)/g of the update matrix
over the wire every defended round. ``robust_relayout_quant`` shrinks it
— int8 rows with per-row scales (4x) or a bf16 cast (2x) — with
DETERMINISTIC rounding so every device dequantizes identical rows and
the defense verdict stays replicated. Knob off must stay bit-identical;
knob on must keep the RFA geometric-median output within a bounded
error; and the collective-traffic accounting (``core/obs`` roofline)
must report the reduced byte count.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core.algframe.types import TrainHyper


def sim_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=8, client_num_per_round=8,
                comm_round=4, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=10_000, random_seed=3,
                enable_defense=True, defense_type="rfa",
                enable_attack=True, attack_type="byzantine_flip",
                byzantine_client_num=2, attack_scale=5.0)
    base.update(kw)
    return Arguments(**base)


def build_sim(args):
    from fedml_tpu import data as data_mod, model as model_mod
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    return TPUSimulator(args, fed, bundle, create_optimizer(args, spec),
                        spec)


def hyper_for(args):
    return TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                      epochs=int(args.epochs))


def run_legs(n_rounds=4, **kw):
    args = sim_args(**kw)
    sim = build_sim(args)
    sim.run_rounds_fused(0, n_rounds, hyper_for(args))
    return sim


def leaves(sim):
    return jax.tree_util.tree_leaves(sim.params)


@pytest.fixture(scope="module")
def dense_leaves():
    """Final params of the knob-absent (dense f32) defended run — the
    golden both the bit-identity and bounded-error tests compare against
    (module-scoped: one compile serves all of them)."""
    return [np.asarray(a) for a in leaves(run_legs())]


class TestKnobOff:
    def test_explicit_off_is_bit_identical(self, dense_leaves):
        """Knob off reproduces today's byte stream AND today's bits: the
        dense f32 all_to_all is the same program, so the final params
        must be array_equal, not merely close."""
        other = run_legs(robust_relayout_quant="off")
        for a, b in zip(dense_leaves, leaves(other)):
            assert np.array_equal(a, np.asarray(b))

    @pytest.mark.parametrize("knob", [None, "none", "false"])
    def test_off_aliases_resolve_to_dense(self, knob):
        """Every off-spelling resolves to the same dense program (the
        resolver is the single dispatch point, so resolver identity ==
        program identity — proven bit-for-bit above for "off")."""
        sim = build_sim(sim_args(robust_relayout_quant=knob))
        assert sim._relayout_quant is None

    def test_unknown_mode_refuses(self):
        with pytest.raises(ValueError, match="robust_relayout_quant"):
            build_sim(sim_args(robust_relayout_quant="fp4"))

    def test_bfloat16_aliases_bf16(self):
        sim = build_sim(sim_args(robust_relayout_quant="bfloat16"))
        assert sim._relayout_quant == "bf16"

    def test_host_path_warns_and_stays_dense(self, caplog):
        """The host-dispatch robust path has no explicit all_to_all to
        quantize — the knob must warn (once, naming the fix) and keep
        the dense re-layout rather than silently changing numerics."""
        with caplog.at_level(logging.WARNING,
                             logger="fedml_tpu.simulation.tpu.engine"):
            sim = build_sim(sim_args(sharded_defense="false",
                                     robust_relayout_quant="int8"))
        assert sim.robust_mode and not sim.robust_fused
        assert sim._relayout_quant is None
        warned = [r for r in caplog.records
                  if "robust_relayout_quant" in r.getMessage()]
        assert len(warned) == 1
        assert "robust_fused" in warned[0].getMessage()


class TestBoundedError:
    """int8/bf16 re-layout perturbs the RFA geometric-median inputs by at
    most half a quantization step per element — the defended params must
    track the dense run within a bound far tighter than a round's worth
    of learning-rate movement (observed: ~5e-4 int8, ~9e-5 bf16 on this
    config), and the quantized run must still converge finitely."""

    @pytest.mark.parametrize("mode,atol", [("int8", 5e-3), ("bf16", 2e-3)])
    def test_rfa_params_track_dense(self, mode, atol, dense_leaves):
        quant = run_legs(robust_relayout_quant=mode)
        for a, b in zip(dense_leaves, leaves(quant)):
            np.testing.assert_allclose(a, np.asarray(b), atol=atol)
            assert np.isfinite(np.asarray(b)).all()

    def test_int8_roundtrip_elementwise_bound(self):
        """The per-row-scale deterministic quantizer itself: the dequant
        error of any element is at most scale/2 = max|row| / 254, and a
        zero row survives (scale clamps to 1, not 0/0)."""
        x = np.random.RandomState(0).randn(16, 257).astype(np.float32)
        x[3] = 0.0
        amax = np.abs(x).max(axis=1, keepdims=True)
        scale = np.where(amax > 0, amax, 1.0) / 127.0
        deq = np.round(x / scale).astype(np.int8).astype(np.float32) * scale
        assert np.abs(deq - x).max() <= (scale / 2 + 1e-7).max()
        assert np.array_equal(deq[3], np.zeros_like(deq[3]))

    def test_single_dispatch_and_compile_once(self, xla_compile_counter):
        """Quantize/dequantize lives INSIDE the fused program — still one
        dispatch per block and zero recompiles across blocks."""
        args = sim_args(comm_round=12, robust_relayout_quant="int8")
        sim = build_sim(args)
        hyper = hyper_for(args)
        sim.run_rounds_fused(0, 4, hyper)
        assert sim.dispatch_stats["dispatches"] == 1
        xla_compile_counter.reset()
        sim.run_rounds_fused(4, 4, hyper)
        sim.run_rounds_fused(8, 4, hyper)
        assert xla_compile_counter.delta() == 0


class TestCollectiveAccounting:
    """core/obs roofline must SEE the shrunken wire: the program's
    predicted collective wire bytes drop when the re-layout rows go over
    as int8/bf16 (the [S] scale all_gather is a rounding error next to
    the [S, D] matrix)."""

    @staticmethod
    def _wire_bytes(**kw):
        from fedml_tpu.core.obs import roofline as obs_roofline
        run_legs(obs_roofline=True, **kw)
        rep = obs_roofline.report("robust_rounds_fused")
        assert rep is not None, "roofline capture missing"
        return float(rep["collective_wire_bytes"])

    def test_quantized_relayout_reduces_wire_bytes(self):
        dense = self._wire_bytes()
        int8 = self._wire_bytes(robust_relayout_quant="int8")
        # int8 stays int8 on every backend: the shared psum/all_gather
        # terms are unchanged, the all_to_all payload shrinks 4x — the
        # total must move materially, not epsilon
        assert int8 < 0.9 * dense
        # bf16 halves the wire on TPU only: the CPU backend's
        # float-normalization pass upcasts bf16 collectives back to f32,
        # so off-TPU the leg proves nothing and just burns a compile
        if jax.default_backend() == "tpu":
            assert self._wire_bytes(robust_relayout_quant="bf16") \
                < 0.9 * dense
