"""SSE token streaming + adapter hot-swap (ISSUE 13): stream/non-stream
bit-parity, the llm_stream knob's off-path, transparent recovery replay
mid-stream (PR 11 composition), hot-swap row semantics (in-flight
requests keep their version), the watched-adapter-dir loop, and the
slow-marked federated adapter flywheel scenario
(train → export → hot-swap → streamed serve → observe).
"""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core.chaos import FaultLedger, FaultPlan, \
    ServingChaosInjector
from fedml_tpu.llm.federated import build_llm, save_adapter_artifacts
from fedml_tpu.serving import SSEStream
from fedml_tpu.serving.batch import AdapterBank
from fedml_tpu.serving.llm_template import (CausalLMPredictor,
                                            ChatCompletionRunner)

pytestmark = pytest.mark.serving


def _args(**kw):
    base = dict(dataset="llm_synthetic", model="causal_lm",
                client_num_in_total=2, client_num_per_round=2,
                comm_round=1, epochs=1, batch_size=4, learning_rate=1e-3,
                random_seed=3, llm_hidden_size=32, llm_num_layers=2,
                llm_num_heads=2, llm_intermediate_size=64,
                llm_max_seq_len=128, lora_rank=4)
    base.update(kw)
    return Arguments(**base)


@pytest.fixture(scope="module")
def setup():
    import jax
    args = _args()
    _, bundle, _, tok = build_llm(args)
    params = bundle.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))
    return args, bundle, params, tok


def _rand_adapter(template, seed):
    import jax
    import jax.numpy as jnp
    leaves, treedef = jax.tree_util.tree_flatten(template)
    key = jax.random.PRNGKey(seed)
    return jax.tree_util.tree_unflatten(
        treedef, [0.3 * jax.random.normal(jax.random.fold_in(key, i),
                                          l.shape, jnp.float32)
                  for i, l in enumerate(leaves)])


def _drain_stream(stream: SSEStream):
    """Consume an SSEStream → (joined_text, finish_choice, n_chunks)."""
    text, finish, n = "", None, 0
    for ev in stream.events:
        n += 1
        choice = ev["choices"][0]
        text += choice["delta"].get("content", "")
        if choice["finish_reason"] is not None:
            finish = choice
    return text, finish, n


# ------------------------------------------------------- streaming ----

class TestStreaming:
    @pytest.fixture(scope="class")
    def preds(self, setup):
        _, bundle, params, tok = setup
        opts = {"slots": 2, "block_size": 8, "prefill_chunk": 8}
        plain = CausalLMPredictor(bundle, params, tokenizer=tok,
                                  mode="batch", batch_opts=dict(opts))
        streaming = CausalLMPredictor(bundle, params, tokenizer=tok,
                                      mode="batch",
                                      batch_opts=dict(opts), stream=True)
        yield plain, streaming
        plain.close()
        streaming.close()

    def test_stream_text_bit_identical_to_nonstream(self, preds):
        plain, streaming = preds
        req = {"messages": [{"role": "user", "content": "stream me a"}],
               "max_tokens": 10, "seed": 4}
        ref = plain.chat(dict(req))
        out = streaming.chat(dict(req, stream=True))
        assert isinstance(out, SSEStream)
        text, finish, _ = _drain_stream(out)
        assert text == ref["choices"][0]["message"]["content"]
        assert finish["finish_reason"] == \
            ref["choices"][0]["finish_reason"]
        assert finish["finish_reason_detail"] == \
            ref["choices"][0]["finish_reason_detail"]
        assert finish["usage"] == ref["usage"]

    def test_knob_off_ignores_stream_flag(self, preds):
        """llm_stream off ⇒ a request carrying "stream": true gets the
        ordinary JSON completion — byte-identical today-path."""
        plain, _ = preds
        out = plain.chat({"messages": [{"role": "user",
                                        "content": "no stream"}],
                          "max_tokens": 6, "stream": True})
        assert isinstance(out, dict)
        assert out["object"] == "chat.completion"

    def test_sampled_stream_reproducible(self, preds):
        _, streaming = preds
        req = {"messages": [{"role": "user", "content": "sample"}],
               "max_tokens": 8, "temperature": 1.4, "seed": 21,
               "stream": True}
        a = _drain_stream(streaming.chat(dict(req)))[0]
        b = _drain_stream(streaming.chat(dict(req)))[0]
        assert a == b

    def test_stream_metric_counted(self, preds):
        from fedml_tpu.core.obs import metrics as obs_metrics
        _, streaming = preds
        before = obs_metrics.REGISTRY.counter(
            "llm_stream_requests_total").value()
        _drain_stream(streaming.chat(
            {"messages": [{"role": "user", "content": "count me"}],
             "max_tokens": 4, "stream": True}))
        after = obs_metrics.REGISTRY.counter(
            "llm_stream_requests_total").value()
        assert after == before + 1


@pytest.mark.chaos
class TestStreamRecoveryReplay:
    def test_recovery_replays_transparently_mid_stream(self, setup):
        """PR 11 composition: an injected NaN mid-decode triggers the
        controlled reset + recompute-from-prompt; the stream pauses over
        the gap and resumes with ONLY new tokens — the delivered text is
        bit-identical to a fault-free run, no duplicates, no holes."""
        _, bundle, params, tok = setup
        ref_pred = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 8, "prefill_chunk": 8})
        req = {"messages": [{"role": "user",
                             "content": "replay this stream"}],
               "max_tokens": 12, "temperature": 1.1, "seed": 9}
        ref = ref_pred.chat(dict(req))
        ref_pred.close()

        inj = ServingChaosInjector(
            FaultPlan(seed=7, serving_nan_at_step=4),
            ledger=FaultLedger())
        pred = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 8, "prefill_chunk": 8,
                        "watchdog_s": 0.3, "max_resets": 4,
                        "max_requeues": 8, "chaos": inj},
            stream=True)
        try:
            out = pred.chat(dict(req, stream=True))
            text, finish, _ = _drain_stream(out)
            assert pred.engine.resets_total >= 1, \
                "the injected NaN never tripped a reset"
            assert text == ref["choices"][0]["message"]["content"]
            assert finish["usage"]["completion_tokens"] == \
                ref["usage"]["completion_tokens"]
        finally:
            pred.close()


class TestGatewayStreamPassthrough:
    def test_gateway_streams_frames_and_degrades_to_json(self, setup):
        """Gateway.stream yields the replica's SSE payloads (no [DONE])
        through the shared failover loop; a stream-knob-off replica's
        JSON body comes back as the single event."""
        from fedml_tpu.serving.autoscale import Gateway, ReplicaSet
        _, bundle, params, tok = setup
        opts = {"slots": 2, "block_size": 8, "prefill_chunk": 8}
        rs = ReplicaSet(
            predictor_factory=lambda: CausalLMPredictor(
                bundle, params, tokenizer=tok, mode="batch",
                batch_opts=dict(opts), stream=True),
            min_replicas=1, max_replicas=1,
            runner_cls=ChatCompletionRunner)
        gw = Gateway(rs, window_s=5.0)
        req = {"messages": [{"role": "user", "content": "gw stream"}],
               "max_tokens": 6, "seed": 2}
        try:
            ref = gw.predict(dict(req), path="/v1/chat/completions",
                             timeout=60)
            frames = [json.loads(d) for d in
                      gw.stream(dict(req, stream=True), timeout=60)]
            text = "".join(c["choices"][0]["delta"].get("content", "")
                           for c in frames)
            assert text == ref["choices"][0]["message"]["content"]
            assert frames[-1]["choices"][0]["finish_reason"] is not None
            # knob respected end-to-end: no "stream" flag -> one JSON
            # event through the same generator surface
            whole = list(gw.stream(dict(req), timeout=60))
            assert len(whole) == 1
            assert json.loads(whole[0])["object"] == "chat.completion"
        finally:
            rs.stop()


# --------------------------------------------------- adapter hot-swap ----

class TestAdapterHotSwap:
    def test_swap_writes_fresh_row_and_pins_protect_old(self, setup):
        _, bundle, params, tok = setup
        bank = AdapterBank(params, capacity=8)
        old_idx = bank.add("silo", _rand_adapter(params, 1))
        old_row = [h[old_idx].copy() for h in bank._host]
        bank.retain_row(old_idx)                 # an in-flight request
        new_idx = bank.swap("silo", _rand_adapter(params, 2))
        assert new_idx != old_idx
        assert bank.index("silo") == new_idx
        # the pinned old row's weights are untouched (the in-flight
        # request keeps the version it started with)
        assert all(np.array_equal(h[old_idx], r)
                   for h, r in zip(bank._host, old_row))
        assert old_idx in bank._retired
        # another swap must NOT reuse the pinned row
        third = bank.swap("other", _rand_adapter(params, 3))
        assert third not in (old_idx, new_idx)
        bank.release_row(old_idx)                # request finished
        assert old_idx not in bank._retired
        # now the row is reusable
        fourth = bank.swap("silo", _rand_adapter(params, 4))
        assert fourth == old_idx

    def test_unpinned_swap_frees_row_immediately(self, setup):
        _, bundle, params, tok = setup
        bank = AdapterBank(params, capacity=4)
        a = bank.add("s", _rand_adapter(params, 1))
        b = bank.swap("s", _rand_adapter(params, 2))
        assert b != a
        c = bank.swap("s", _rand_adapter(params, 3))
        assert c == a                            # the freed row cycles
        assert bank.swaps == 2

    def test_engine_pins_adapter_for_request_lifetime(self, setup):
        """A hot-swap mid-request must not change the weights a running
        request decodes with: its output equals the pre-swap solo run."""
        _, bundle, params, tok = setup
        pred = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 8, "prefill_chunk": 8,
                        "max_adapters": 8})
        bank = pred.adapter_bank
        try:
            v1 = _rand_adapter(params, 31)
            bank.add("siloX", v1)
            before = pred.generate("pin probe", max_new_tokens=8,
                                   adapter="siloX")["text"]
            idx_v1 = bank.index("siloX")
            bank.retain_row(idx_v1)              # simulate in-flight pin
            bank.swap("siloX", _rand_adapter(params, 32))
            # the retired, pinned row still serves v1 weights: a request
            # that resolved before the swap decodes unchanged
            fut = pred.engine.submit(
                pred._encode_prompt("pin probe", 8), max_new_tokens=8,
                adapter_idx=idx_v1)
            out = fut.result(timeout=60)
            assert tok.decode(out["ids"]) == before
            # new requests by NAME get the new version
            after = pred.generate("pin probe", max_new_tokens=8,
                                  adapter="siloX")["text"]
            assert after != before
            bank.release_row(idx_v1)
        finally:
            pred.close()

    def test_watched_dir_swaps_live(self, setup, tmp_path):
        """The zero-restart loop: re-exporting into the watched dir goes
        live within a poll without touching the engine (zero recompiles
        — the stack refresh is a host→device transfer)."""
        _, bundle, params, tok = setup
        v1, v2 = _rand_adapter(params, 41), _rand_adapter(params, 42)
        save_adapter_artifacts({"siloW": v1}, str(tmp_path))
        bank = AdapterBank.from_artifacts(str(tmp_path), capacity=8)
        pred = CausalLMPredictor(
            bundle, params, tokenizer=tok, mode="batch",
            batch_opts={"slots": 2, "block_size": 8, "prefill_chunk": 8},
            adapter_bank=bank)
        try:
            out_v1 = pred.generate("watch probe", max_new_tokens=8,
                                   adapter="siloW")["text"]
            bank.watch_dir(str(tmp_path), poll_s=0.1)
            time.sleep(0.15)                     # initial scan settles
            assert bank.swaps == 0               # no spurious swap
            # a fresh federated export lands (atomic os.replace inside)
            os.utime(str(tmp_path))              # ensure mtime moves
            save_adapter_artifacts({"siloW": v2, "siloNew": v1},
                                   str(tmp_path))
            deadline = time.time() + 10
            while time.time() < deadline and bank.swaps < 2:
                time.sleep(0.05)
            assert bank.swaps >= 2               # siloW update + siloNew
            assert bank.has("siloNew")
            out_v2 = pred.generate("watch probe", max_new_tokens=8,
                                   adapter="siloW")["text"]
            assert out_v2 != out_v1              # the new version serves
        finally:
            pred.close()
        assert bank._watch_thread is None        # close() stopped it


# --------------------------------- the federated adapter flywheel ----

@pytest.mark.slow
class TestAdapterFlywheelE2E:
    def test_train_export_hotswap_stream_observe(self, tmp_path):
        """ROADMAP item 1's loop, end to end: federated LoRA fine-tune →
        adapter export → served bank with a watcher → a NEW round's
        re-export hot-swaps live → streamed chat over HTTP uses the bank
        → /debug/state and /metrics observe the whole thing."""
        from fedml_tpu.llm.federated import run_federated_llm
        from fedml_tpu.serving import save_model

        export_dir = str(tmp_path / "adapters")
        args = _args(comm_round=1,
                     llm_adapter_export_dir=export_dir,
                     llm_adapter_personalize_steps=1)
        result = run_federated_llm(args)
        assert os.path.exists(os.path.join(export_dir, "manifest.json"))
        params_path = str(tmp_path / "model.fmtpu")
        save_model(result["params"], params_path)

        serve_args = _args(
            llm_serving_mode="batch", llm_adapter_dir=export_dir,
            llm_adapter_watch_s=0.1, llm_stream=True,
            llm_prefix_cache=True, llm_prefill_batch=4,
            serving_slots=4, serving_kv_block_size=8,
            serving_prefill_chunk=8)
        pred = CausalLMPredictor.from_artifact(serve_args, params_path)
        runner = ChatCompletionRunner(pred)
        port = runner.start()
        try:
            bank = pred.adapter_bank
            assert bank.has("global") and bank.has("silo_0")
            # a "new federated round" re-exports: hot-swap goes live
            import jax
            leaves, treedef = jax.tree_util.tree_flatten(
                result["params"])
            bumped = jax.tree_util.tree_unflatten(
                treedef, [l + 0.05 for l in leaves])
            save_adapter_artifacts({"global": result["params"],
                                    "silo_0": bumped}, export_dir)
            deadline = time.time() + 10
            while time.time() < deadline and bank.swaps < 1:
                time.sleep(0.05)
            assert bank.swaps >= 1

            # streamed chat over HTTP against the swapped bank
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/chat/completions",
                data=json.dumps({
                    "model": "silo_0",
                    "messages": [{"role": "user",
                                  "content": "flywheel check"}],
                    "max_tokens": 6, "stream": True}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                assert "text/event-stream" in r.headers["Content-Type"]
                frames = [ln.decode().strip() for ln in r if ln.strip()]
            datas = [f[6:] for f in frames if f.startswith("data: ")]
            assert datas[-1] == "[DONE]"
            chunks = [json.loads(d) for d in datas[:-1]]
            assert chunks[-1]["choices"][0]["finish_reason"] is not None

            # observe: /debug/state exposes the prefix index; /metrics
            # exposes swaps and stream counters
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/state",
                    timeout=10) as r:
                dbg = json.load(r)
            assert "prefix_cache" in dbg["scheduler"]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                metrics_text = r.read().decode()
            assert "llm_adapter_swaps_total" in metrics_text
            assert "llm_stream_requests_total" in metrics_text
        finally:
            runner.stop()
            pred.close()
