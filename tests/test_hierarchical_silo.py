"""Hierarchical cross-silo: intra-silo data parallelism over an inner
data-axis mesh; 2 silos x 2 devices each, parity vs flat cross-silo
(reference cross_silo/client/fedml_client_slave_manager.py:9 +
process_group_manager.py:8 collapse into one SPMD program per silo)."""

import numpy as np

from fedml_tpu import data as data_mod
from fedml_tpu import model as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_silo.hierarchical import (
    run_hierarchical_cross_silo_inproc)
from fedml_tpu.cross_silo.horizontal.runner import run_cross_silo_inproc


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=2, client_num_per_round=2,
                comm_round=3, epochs=1, batch_size=32, learning_rate=0.1,
                frequency_of_the_test=1, random_seed=5,
                training_type="cross_silo", scenario="hierarchical")
    base.update(kw)
    return Arguments(**base)


def test_two_silos_two_devices_each_matches_flat():
    args = make_args()
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    r_hier = run_hierarchical_cross_silo_inproc(args, fed, bundle,
                                                devices_per_silo=2)
    assert r_hier is not None and len(r_hier["history"]) == 3

    args2 = make_args(scenario="horizontal")
    fed2, _ = data_mod.load(args2)
    bundle2 = model_mod.create(args2, output_dim)
    r_flat = run_cross_silo_inproc(args2, fed2, bundle2)

    # data-parallel sharding must not change the math: same final model
    # up to reduction-order noise
    hp = np.concatenate([np.asarray(l).ravel() for l in
                         __import__("jax").tree_util.tree_leaves(
                             r_hier["params"])])
    fp = np.concatenate([np.asarray(l).ravel() for l in
                         __import__("jax").tree_util.tree_leaves(
                             r_flat["params"])])
    np.testing.assert_allclose(hp, fp, rtol=2e-3, atol=2e-4)
    assert abs(r_hier["final_test_acc"] - r_flat["final_test_acc"]) < 0.02


def test_silo_step_is_actually_sharded():
    """The silo trainer's batch placement really spans its device slice."""
    import jax
    from fedml_tpu.core.algframe.client_trainer import make_trainer_spec
    from fedml_tpu.cross_silo.hierarchical import HierarchicalSiloTrainer
    from fedml_tpu.optimizers.registry import create_optimizer

    args = make_args()
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    spec = make_trainer_spec(fed, bundle)
    opt = create_optimizer(args, spec)
    devs = jax.devices()[:2]
    tr = HierarchicalSiloTrainer(args, fed, bundle, spec, opt, devs)
    cdata = jax.tree_util.tree_map(lambda a: a[0], fed.train)
    placed = tr._place(cdata)
    assert len(placed.x.sharding.device_set) == 2
    params, n, metrics = tr.train(tr.params_template, 0, 0)
    assert n > 0 and np.isfinite(metrics["train_loss"])
