"""Unified wire pipeline (core/wire, ISSUE 19): lane-packed field
quantization round-trip bounds, overflow-safe K-lane sums below p,
mask-then-sum == sum-then-unmask bit-exactness, the adaptive keep-ratio
schedule, the per-stage byte ledger, wire-state checkpoint resume
parity, and knob-off byte-identity on the gossip and cross-device
transports (the sync cross-silo pins live in test_comm_compression)."""

import tempfile
import types

import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.core.distributed.communication.message import WIRE_STATS
from fedml_tpu.core.mpc import P, expand_mask
from fedml_tpu.core.selection.stats import ClientStatsStore
from fedml_tpu.core.wire import (AdaptiveRatioBounds, EncodedUpdate,
                                 LanePlan, adaptive_keep_ratio,
                                 decode_update, encode_update, field_encode,
                                 lane_dequantize_sum, lane_pack,
                                 lane_quantize, lane_unpack_sum, mask_packed,
                                 pack_optional_vec, plan_for, suggest_scale,
                                 unpack_optional_vec, wire_checkpointer,
                                 wire_state_template)
from fedml_tpu.utils.compression import CommCompressionSpec

pytestmark = pytest.mark.wire


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=4, client_num_per_round=4,
                comm_round=3, epochs=1, batch_size=32, learning_rate=0.1,
                random_seed=13, training_type="cross_silo")
    base.update(kw)
    return Arguments(**base)


# ---------------------------------------------------------------------------
# lane plan geometry
# ---------------------------------------------------------------------------

class TestLanePlan:
    @pytest.mark.parametrize("bits,k_max,width,lanes", [
        (4, 4, 6, 5),     # the bench leg: 0.8 B/coord
        (4, 16, 8, 3),
        (8, 16, 12, 2),
        (16, 8, 19, 1),
    ])
    def test_geometry(self, bits, k_max, width, lanes):
        plan = plan_for(bits, k_max)
        assert plan.width == width and plan.lanes == lanes
        assert plan.bytes_per_coord() == pytest.approx(4.0 / lanes)
        # headroom invariant: a full lane sum never reaches the next lane
        assert k_max * ((1 << bits) - 1) <= (1 << width) - 1
        # and the packed budget stays under the field prime
        assert plan.lanes * plan.width <= 30

    def test_packed_len_ceil(self):
        plan = plan_for(4, 4)   # 5 lanes
        assert plan.packed_len(10) == 2
        assert plan.packed_len(11) == 3

    def test_invalid_plans_raise(self):
        with pytest.raises(ValueError):
            plan_for(5, 4)          # bits not in (4, 8, 16)
        with pytest.raises(ValueError):
            plan_for(4, 0)          # k_max < 1
        with pytest.raises(ValueError):
            plan_for(16, 1 << 15)   # width 31 > 30-bit budget

    def test_wire_roundtrip(self):
        plan = plan_for(8, 16)
        assert LanePlan.from_wire(plan.to_wire()) == plan


# ---------------------------------------------------------------------------
# quantization round-trip + overflow safety
# ---------------------------------------------------------------------------

class TestLaneQuant:
    @pytest.mark.parametrize("bits", [4, 8, 16])
    def test_roundtrip_error_bound(self, bits):
        """Stochastic rounding without clipping: per-coordinate error
        strictly below one quantization step."""
        plan = plan_for(bits, 4)
        rng = np.random.default_rng(0)
        x = rng.normal(size=257).astype(np.float32)
        scale = suggest_scale(float(np.abs(x).max()), plan)
        packed, residual = lane_quantize(x, scale, plan,
                                         np.random.default_rng(1))
        dec = lane_dequantize_sum(packed, 1, scale, plan, x.shape[0])
        assert np.max(np.abs(dec - x)) < scale + 1e-6
        # the residual IS the quantization error, exactly
        np.testing.assert_allclose(residual, x - dec, atol=1e-6)

    def test_residual_algebra_with_ef_carry(self):
        """field_encode: scale*q + new_residual == delta + old_residual
        (error feedback loses nothing)."""
        plan = plan_for(4, 4)
        rng = np.random.default_rng(2)
        delta = rng.normal(size=100).astype(np.float32)
        old = rng.normal(scale=0.1, size=100).astype(np.float32)
        scale = suggest_scale(4.0, plan)
        packed, new = field_encode(delta, scale, plan, old,
                                   np.random.default_rng(3))
        dec = lane_dequantize_sum(packed, 1, scale, plan, 100)
        np.testing.assert_allclose(dec + new, delta + old, atol=1e-5)

    def test_tail_padding_decodes_to_zero(self):
        plan = plan_for(4, 4)   # 5 lanes: d=7 pads 3 tail lanes
        u = np.full(7, plan.offset + 3, np.uint64)
        packed = lane_pack(u, plan)
        s = lane_unpack_sum(packed.astype(np.uint64), 1, plan, 7)
        assert np.all(s == 3)
        # the padded lanes (coords 7..9 of the 2 words) decode to 0
        full = lane_unpack_sum(packed.astype(np.uint64), 1, plan, 10)
        assert np.all(full[7:] == 0)

    @pytest.mark.parametrize("bits,k_max", [(4, 4), (4, 16), (8, 16)])
    def test_worst_case_k_sum_below_p(self, bits, k_max):
        """All-qmax vectors from k_max clients: the packed integer sum
        stays below 2**30 < p (no mod-p wrap, no lane carry)."""
        plan = plan_for(bits, k_max)
        d = 64
        u = np.full(d, plan.offset + plan.qmax, np.uint64)  # max encoding
        packed = lane_pack(u, plan).astype(np.uint64)
        total = packed * np.uint64(k_max)                   # exact int sum
        assert int(total.max()) < 2**30 < P
        s = lane_unpack_sum(total, k_max, plan, d)
        assert np.all(s == k_max * plan.qmax)

    def test_unpack_rejects_k_above_plan(self):
        plan = plan_for(4, 4)
        with pytest.raises(ValueError, match="k_max"):
            lane_unpack_sum(np.zeros(4, np.uint64), 5, plan, 16)


# ---------------------------------------------------------------------------
# mask-then-sum == sum-then-unmask (the SecAgg-compatibility property)
# ---------------------------------------------------------------------------

class TestMaskedSum:
    @pytest.mark.parametrize("bits,k", [(4, 4), (4, 16), (8, 16), (16, 8)])
    def test_bit_exact_mask_cancellation(self, bits, k):
        plan = plan_for(bits, k)
        d = 131
        plen = plan.packed_len(d)
        rng = np.random.default_rng(bits * 100 + k)
        scale = suggest_scale(4.0, plan)
        packs = []
        for i in range(k):
            vec = rng.normal(size=d).astype(np.float32) * 2.0
            packed, _ = field_encode(vec, scale, plan, None,
                                     np.random.default_rng(1000 + i))
            packs.append(packed.astype(np.uint64))
        # pairwise masks with integer seeds; +s_ij for i<j, -s_ij else
        masked_total = np.zeros(plen, np.uint64)
        plain_total = np.zeros(plen, np.uint64)
        for i in range(k):
            m = packs[i] % P
            for j in range(k):
                if i == j:
                    continue
                s = expand_mask((min(i, j) << 8) ^ max(i, j),
                                plen).astype(np.uint64)
                m = (m + s) % P if i < j else (m + P - s) % P
            masked_total = (masked_total + m) % P
            plain_total = (plain_total + packs[i]) % P
        # masks cancel bit-for-bit...
        assert np.array_equal(masked_total, plain_total)
        # ...and the decoded sum is bit-identical either way
        a = lane_dequantize_sum(masked_total, k, scale, plan, d)
        b = lane_dequantize_sum(plain_total, k, scale, plan, d)
        assert np.array_equal(a, b)

    def test_mask_packed_helper_roundtrip(self):
        plan = plan_for(4, 4)
        rng = np.random.default_rng(7)
        vec = rng.normal(size=50).astype(np.float32)
        scale = suggest_scale(4.0, plan)
        packed, _ = field_encode(vec, scale, plan, None,
                                 np.random.default_rng(8))
        plen = packed.shape[0]
        mask = expand_mask(12345, plen).astype(np.uint64)
        masked = mask_packed(packed, mask)
        unmasked = (masked.astype(np.uint64) + np.uint64(P) - mask) \
            % np.uint64(P)
        assert np.array_equal(unmasked.astype(np.uint32), packed)


# ---------------------------------------------------------------------------
# adaptive keep-ratio schedule
# ---------------------------------------------------------------------------

class TestAdaptiveRatio:
    def bounds(self, **kw):
        base = dict(ratio_min=0.02, ratio_max=0.2, latency_budget_s=10.0)
        base.update(kw)
        return AdaptiveRatioBounds(**base)

    def test_invalid_bounds_raise(self):
        with pytest.raises(ValueError):
            AdaptiveRatioBounds(0.0, 0.1)
        with pytest.raises(ValueError):
            AdaptiveRatioBounds(0.5, 0.1)
        with pytest.raises(ValueError):
            AdaptiveRatioBounds(0.1, 0.5, latency_budget_s=0.0)

    def test_no_stats_is_ratio_max(self):
        b = self.bounds()
        assert adaptive_keep_ratio(b, None, [1, 2]) == b.ratio_max
        assert adaptive_keep_ratio(b, ClientStatsStore(4), []) \
            == b.ratio_max

    def test_unobserved_cohort_is_ratio_max(self):
        stats = ClientStatsStore(8)
        assert adaptive_keep_ratio(self.bounds(), stats, [1, 2, 3]) \
            == self.bounds().ratio_max

    def test_latency_pressure_tightens_ratio(self):
        b = self.bounds()
        stats = ClientStatsStore(8)
        stats.record_latency(2, 5.0)            # half the budget
        mid = adaptive_keep_ratio(b, stats, [1, 2, 3])
        assert b.ratio_min < mid < b.ratio_max
        stats.record_latency(3, 50.0)           # way over budget: clamps
        assert adaptive_keep_ratio(b, stats, [1, 2, 3]) == b.ratio_min

    def test_dropout_pressure_tightens_ratio(self):
        b = self.bounds(latency_budget_s=None)
        stats = ClientStatsStore(8)
        for _ in range(30):
            stats.record_availability(1, participated=False)
        assert adaptive_keep_ratio(b, stats, [1, 2]) < b.ratio_max

    def test_deterministic(self):
        stats = ClientStatsStore(8)
        stats.record_latency(1, 3.0)
        b = self.bounds()
        assert adaptive_keep_ratio(b, stats, [1, 2]) \
            == adaptive_keep_ratio(b, stats, [1, 2])


# ---------------------------------------------------------------------------
# the encode seam + per-stage byte ledger
# ---------------------------------------------------------------------------

class TestEncodeSeam:
    def test_knob_off_is_noop(self):
        vec = np.ones(16, np.float32)
        res_in = np.zeros(16, np.float32)
        enc = encode_update(vec, spec=None, residual=res_in)
        assert isinstance(enc, EncodedUpdate)
        assert enc.payload is None and enc.payload_bytes == 0
        assert enc.residual is res_in           # untouched, not copied
        assert enc.raw_bytes == vec.nbytes

    def test_delta_roundtrip_with_base(self):
        import jax
        spec = CommCompressionSpec(method="topk_qsgd", ratio=0.5)
        rng = np.random.default_rng(0)
        base = rng.normal(size=64).astype(np.float32)
        vec = base + rng.normal(scale=0.1, size=64).astype(np.float32)
        enc = encode_update(vec, base=base, spec=spec,
                            rng=jax.random.PRNGKey(0))
        assert enc.payload is not None and enc.payload_bytes > 0
        out = decode_update(enc.payload, base=base)
        # EF residual holds exactly what the wire dropped
        np.testing.assert_allclose(out + enc.residual, vec, atol=1e-5)

    def test_decode_rejects_dense(self):
        with pytest.raises(ValueError):
            decode_update({"not": "a blob"})

    def test_stage_ledger_by_msg_type(self):
        import jax
        WIRE_STATS.reset()
        spec = CommCompressionSpec(method="topk_qsgd", ratio=0.25)
        vec = np.random.default_rng(1).normal(size=100).astype(np.float32)
        encode_update(vec, spec=spec, rng=jax.random.PRNGKey(0),
                      msg_type=3)
        snap = WIRE_STATS.snapshot()["by_stage"]
        rec = snap.get("3", snap.get(3))
        assert rec["raw"] == 400
        assert 0 < rec["sparsified"] < rec["raw"]
        WIRE_STATS.reset()
        assert WIRE_STATS.snapshot()["by_stage"] == {}


# ---------------------------------------------------------------------------
# wire-state checkpointing: resume == uninterrupted
# ---------------------------------------------------------------------------

def _client_manager_stub(tmpdir, d=32):
    """A ClientMasterManager carrying only the wire-state attrs (the
    repo's __new__ idiom for FSM-free unit tests)."""
    from fedml_tpu.cross_silo.client.fedml_client_master_manager import \
        ClientMasterManager
    m = ClientMasterManager.__new__(ClientMasterManager)
    m.rank = 1
    m.round_idx = 0
    m._cc_residual = None
    m._global_vec = None
    m.trainer = types.SimpleNamespace(
        params_to_vec=lambda t: np.asarray(t, np.float32),
        params_template=np.zeros(d, np.float32))
    m._wire_ckpt = wire_checkpointer(
        make_args(checkpoint_dir=tmpdir, checkpoint_every_rounds=1),
        "client_1")
    return m


class TestWireCheckpoint:
    def test_optional_vec_pack_roundtrip(self):
        f, a = pack_optional_vec(None, 4)
        assert unpack_optional_vec(f, a) is None
        v = np.arange(4, dtype=np.float32)
        f, a = pack_optional_vec(v, 4)
        np.testing.assert_array_equal(unpack_optional_vec(f, a), v)

    def test_checkpointer_off_without_knobs(self):
        assert wire_checkpointer(make_args(), "client_1") is None
        assert wire_checkpointer(
            make_args(checkpoint_dir="/tmp/x"), "s") is None

    def test_client_resume_matches_uninterrupted(self, tmp_path):
        """The satellite pin: a client whose wire state is restored from
        the checkpoint produces the SAME compressed uplinks as one that
        never crashed — EF residual and broadcast base both survive."""
        import jax
        d = 32
        spec = CommCompressionSpec(method="topk_qsgd", ratio=0.25)
        rng = np.random.default_rng(5)
        globals_ = [rng.normal(size=d).astype(np.float32)
                    for _ in range(4)]
        trained = [g + rng.normal(scale=0.1, size=d).astype(np.float32)
                   for g in globals_]

        def run_rounds(mgr, start, stop):
            blobs = []
            for r in range(start, stop):
                mgr.round_idx = r
                mgr._global_vec = globals_[r]
                enc = encode_update(trained[r], base=mgr._global_vec,
                                    spec=spec, residual=mgr._cc_residual,
                                    rng=jax.random.fold_in(
                                        jax.random.PRNGKey(97), r))
                mgr._cc_residual = enc.residual
                blobs.append(enc.payload)
                mgr._save_wire_state()
            return blobs

        uninterrupted = _client_manager_stub(str(tmp_path / "a"), d)
        blobs_a = run_rounds(uninterrupted, 0, 4)
        uninterrupted._wire_ckpt.close()

        crashed = _client_manager_stub(str(tmp_path / "b"), d)
        blobs_b = run_rounds(crashed, 0, 2)
        crashed._wire_ckpt.close()           # "crash" after round 1 save
        resumed = _client_manager_stub(str(tmp_path / "b"), d)
        resumed._restore_wire_state()
        np.testing.assert_array_equal(resumed._cc_residual,
                                      crashed._cc_residual)
        blobs_b += run_rounds(resumed, 2, 4)
        resumed._wire_ckpt.close()

        for a, b in zip(blobs_a, blobs_b):
            assert set(a) == set(b)
            for key in ("v", "i"):
                if key in a:
                    np.testing.assert_array_equal(a[key], b[key])

    def test_async_ef_carry_roundtrip(self, tmp_path):
        """The async server's per-sender pour residuals survive a
        save/restore cycle (versions, vectors, compressed-sender set)."""
        from fedml_tpu.cross_silo.server.async_server import \
            AsyncFedMLServerManager

        d = 16
        args = make_args(checkpoint_dir=str(tmp_path),
                         checkpoint_every_rounds=1)

        def stub():
            m = AsyncFedMLServerManager.__new__(AsyncFedMLServerManager)
            m.args = args
            m.client_num = 4
            m.aggregator = types.SimpleNamespace(
                _base_ring={0: np.zeros(d, np.float32)},
                _ef_carry={}, _compressed_senders=set(), version=0)
            m._wire_ckpt = wire_checkpointer(args, "async_server")
            return m

        saver = stub()
        carry = np.arange(d, dtype=np.float32)
        saver.aggregator._ef_carry = {2: (3, carry)}
        saver.aggregator._compressed_senders = {1, 2}
        saver._save_wire_state(5)
        saver._wire_ckpt.close()

        loader = stub()
        loader._restore_wire_state()
        loader._wire_ckpt.close()
        assert loader.aggregator._compressed_senders == {1, 2}
        assert set(loader.aggregator._ef_carry) == {2}
        cv, cres = loader.aggregator._ef_carry[2]
        assert cv == 3
        np.testing.assert_array_equal(cres, carry)


# ---------------------------------------------------------------------------
# defended async pour: excluded compressed rows re-enter via the carry
# ---------------------------------------------------------------------------

class TestAsyncEFCarry:
    def test_excluded_row_carried_and_rebased(self):
        """A defense-excluded compressed sender's re-based row is stored,
        re-based across the server movement it missed, and folded into
        the sender's next row before the next defense pass."""
        from fedml_tpu.cross_silo.server.async_server import \
            AsyncFedMLAggregator

        d = 8
        agg = AsyncFedMLAggregator.__new__(AsyncFedMLAggregator)
        agg._ef_carry = {}
        agg._compressed_senders = {1}
        base0 = np.zeros(d, np.float32)
        base1 = np.full(d, 0.5, np.float32)
        agg._base_ring = {0: base0, 1: base1}
        agg.version = 1
        # simulate the pour bookkeeping: the row excluded at version 0
        row = np.full(d, 2.0, np.float32)
        agg._ef_carry[1] = (0, row)
        # re-base to version 1 exactly as the pour does
        base = agg._base_ring[agg.version]
        cv, cres = agg._ef_carry.pop(1)
        rebased = cres - (base - agg.base_for(cv))
        # stored row satisfied base0 + row = target; the re-based one
        # must satisfy base1 + rebased = the same target
        np.testing.assert_allclose(base + rebased, base0 + row)


# ---------------------------------------------------------------------------
# refused combinations fail fast (README compatibility matrix)
# ---------------------------------------------------------------------------

class TestRefusedCombos:
    def test_secagg_refuses_sparsifiers(self):
        pytest.importorskip("cryptography")
        from fedml_tpu.cross_silo.secagg import _refuse_sparsified_wire
        with pytest.raises(ValueError, match="support sets"):
            _refuse_sparsified_wire(make_args(comm_compression="topk"))
        _refuse_sparsified_wire(make_args())          # knob off: fine
        _refuse_sparsified_wire(make_args(secagg_compress_bits=4))  # lanes ok

    def test_lightsecagg_refuses_wire_compression(self):
        pytest.importorskip("cryptography")
        from fedml_tpu.cross_silo.lightsecagg import \
            _refuse_wire_compression
        with pytest.raises(ValueError, match="incompatible"):
            _refuse_wire_compression(make_args(secagg_compress_bits=4))
        with pytest.raises(ValueError, match="incompatible"):
            _refuse_wire_compression(make_args(comm_compression="topk"))
        _refuse_wire_compression(make_args())


# ---------------------------------------------------------------------------
# knob-off byte identity per transport (session level)
# ---------------------------------------------------------------------------

class TestKnobOffByteIdentity:
    _dense_gossip = None   # memoized across tests: 3 sessions, not 4

    def _gossip_bytes(self, **kw):
        from fedml_tpu import data as data_mod
        from fedml_tpu import model as model_mod
        from fedml_tpu.cross_silo.decentralized import run_gossip_inproc
        args = make_args(comm_round=2, client_num_in_total=3,
                         client_num_per_round=3, **kw)
        fed, od = data_mod.load(args)
        bundle = model_mod.create(args, od)
        WIRE_STATS.reset()
        result = run_gossip_inproc(args, fed, bundle)
        snap = WIRE_STATS.snapshot()
        return snap, result

    def _dense(self):
        if TestKnobOffByteIdentity._dense_gossip is None:
            TestKnobOffByteIdentity._dense_gossip = self._gossip_bytes()
        return TestKnobOffByteIdentity._dense_gossip

    def test_gossip_knob_off_byte_identical_and_unstaged(self):
        snap1, r1 = self._dense()
        snap2, r2 = self._gossip_bytes(gossip_compression=None)
        # byte-for-byte identical wire, nothing enters the pipeline
        assert snap1["by_type"] == snap2["by_type"]
        assert snap1["by_stage"] == {} and snap2["by_stage"] == {}
        assert r1["final_test_acc"] == r2["final_test_acc"]

    def test_gossip_knob_on_shrinks_n2n(self):
        snap_off, _ = self._dense()
        snap_on, r_on = self._gossip_bytes(gossip_compression="topk_qsgd",
                                           comm_compression_ratio=0.1)
        key = next(k for k in snap_off["by_type"] if str(k) == "301")
        assert snap_on["by_type"][key]["bytes"] \
            < snap_off["by_type"][key]["bytes"]
        assert snap_on["by_stage"]            # the ledger saw the stages
        assert r_on["final_test_acc"] is not None

    def test_cross_device_knob_off_artifacts_dense(self, tmp_path):
        from fedml_tpu import data as data_mod
        from fedml_tpu import model as model_mod
        from fedml_tpu.cross_device.runner import run_cross_device_inproc

        def session(subdir, **kw):
            args = make_args(training_type="cross_device", comm_round=2,
                             client_num_in_total=2, client_num_per_round=2,
                             model_file_cache_dir=str(tmp_path / subdir),
                             **kw)
            fed, od = data_mod.load(args)
            bundle = model_mod.create(args, od)
            WIRE_STATS.reset()
            result = run_cross_device_inproc(args, fed, bundle)
            return WIRE_STATS.snapshot(), result

        snap_off, r_off = session("off")
        assert snap_off["by_stage"] == {}     # dense artifacts: no stages
        snap_on, r_on = session("on", device_wire_compression="topk_qsgd",
                                comm_compression_ratio=0.1)
        rec = snap_on["by_stage"].get("d2s_model")
        assert rec and 0 < rec["sparsified"] < rec["raw"]
        assert r_off["final_test_acc"] is not None
        assert r_on["final_test_acc"] is not None
