"""Adaptive participant selection & client reputation (core/selection).

Covers (1) the sampling-stream satellite — the legacy stream is
bit-compatible with the reference's global-seed draw WITHOUT clobbering
the process-global RNG, the seeded stream folds random_seed in; (2) the
ClientStatsStore (Beta-posterior dropout, loss ring, latency EMA, AIMD
reputation, checkpoint round-trip); (3) strategy behavior and determinism
given (seed, observed history); (4) the engine seam — default knobs
produce bit-identical schedules, reputation benches defense-excluded
clients as renormalized in-program dropout, adaptive over-sampling grows
the cohort from observed dropout, crash-resume replays identical
selections, and the fused robust program still compiles exactly once with
selection enabled; (5) the cross-silo silo-selection seam.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import fedml_tpu
from fedml_tpu.arguments import Arguments
from fedml_tpu.core.selection import (ClientStatsStore, SelectionManager,
                                      create_strategy, slot_placement)
from fedml_tpu.simulation.sampling import (client_sampling,
                                           sampling_stream_from_args)

pytestmark = pytest.mark.selection


def make_args(**kw):
    base = dict(dataset="synthetic_mnist", model="lr",
                client_num_in_total=8, client_num_per_round=8,
                comm_round=3, epochs=1, batch_size=16, learning_rate=0.1,
                frequency_of_the_test=2, random_seed=42)
    base.update(kw)
    return Arguments(**base)


def build_sim(args):
    from fedml_tpu import data as data_mod, model as model_mod
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    return TPUSimulator(args, fed, bundle, create_optimizer(args, spec),
                        spec)


def hyper_for(args):
    from fedml_tpu.core.algframe.types import TrainHyper
    return TrainHyper(learning_rate=jnp.float32(args.learning_rate),
                      epochs=int(args.epochs))


# --- sampling streams (satellite) -------------------------------------------

class TestSamplingStreams:
    def test_legacy_stream_matches_reference_draw(self):
        """RandomState(round) must reproduce the exact sequence the old
        np.random.seed(round) + global np.random.choice produced."""
        for r in range(6):
            np.random.seed(r)
            ref = list(np.random.choice(range(20), 7, replace=False))
            got = client_sampling(r, 20, 7, random_seed=123,
                                  stream="legacy")
            assert [int(c) for c in ref] == [int(c) for c in got]

    def test_legacy_stream_does_not_clobber_global_rng(self):
        np.random.seed(777)
        expect = np.random.random(4)
        np.random.seed(777)
        client_sampling(3, 20, 7, stream="legacy")
        got = np.random.random(4)
        np.testing.assert_array_equal(expect, got)

    def test_seeded_stream_respects_random_seed(self):
        a = client_sampling(2, 30, 8, random_seed=1, stream="seeded")
        b = client_sampling(2, 30, 8, random_seed=2, stream="seeded")
        c = client_sampling(2, 30, 8, random_seed=1, stream="seeded")
        assert a == c
        assert a != b  # different seeds, different cohorts

    def test_stream_knob_validated(self):
        with pytest.raises(ValueError):
            client_sampling(0, 10, 4, stream="mystery")
        with pytest.raises(ValueError):
            sampling_stream_from_args(make_args(sampling_stream="nope"))
        assert sampling_stream_from_args(make_args()) == "legacy"


# --- ClientStatsStore -------------------------------------------------------

class TestStatsStore:
    def test_dropout_posterior(self):
        st = ClientStatsStore(4)
        p0 = st.dropout_posterior_mean()[0]
        assert 0.0 < p0 < 0.1  # weakly-informative prior
        for _ in range(10):
            st.record_availability(0, participated=False)
            st.record_availability(1, participated=True)
        post = st.dropout_posterior_mean()
        assert post[0] > 0.3
        assert post[1] < p0
        assert 0.0 < st.population_dropout_mean() < 1.0

    def test_loss_ring_and_queries(self):
        st = ClientStatsStore(3, loss_window=4)
        assert np.isinf(st.last_loss()[0])
        assert np.isnan(st.rms_loss()[0])
        for i, loss in enumerate([5.0, 4.0, 3.0, 2.0, 1.0]):
            st.record_loss(0, loss)
        assert st.last_loss()[0] == 1.0  # ring wrapped
        assert np.isclose(st.rms_loss()[0],
                          np.sqrt(np.mean(np.square([4.0, 3.0, 2.0, 1.0]))))
        st.record_loss(1, float("nan"))  # ignored, not poisoning the ring
        assert np.isinf(st.last_loss()[1])

    def test_latency_ema(self):
        st = ClientStatsStore(2, ema_alpha=0.5)
        st.record_latency(0, 2.0)
        assert st.ema_latency[0] == 2.0  # first sample seeds the EMA
        st.record_latency(0, 4.0)
        assert np.isclose(st.ema_latency[0], 3.0)

    def test_reputation_normalized_posterior(self):
        st = ClientStatsStore(4)
        np.testing.assert_array_equal(st.reputation, np.ones(4))
        for _ in range(6):  # client 0 always excluded, 1 and 2 kept
            st.record_verdict([0, 1, 2], [0.0, 1.0, 1.0])
        rep = st.reputation
        assert rep[0] < 0.3  # consistently excluded vs cohort -> branded
        assert rep[1] == 1.0 and rep[2] == 1.0
        assert rep[3] == 1.0  # unobserved: innocent until evidence

    def test_reputation_tolerates_harsh_selection_defense(self):
        """krum keeps m of K every round, so honest clients are excluded
        at the baseline rate too — the NORMALIZED posterior must not
        brand them, only the consistently-worse-than-cohort client."""
        st = ClientStatsStore(4)
        rng = np.random.default_rng(0)
        for _ in range(30):
            # defense keeps 2 of 4; client 3 never kept, others rotate
            kept = rng.choice(3, 2, replace=False)
            v = np.zeros(4)
            v[kept] = 1.0
            st.record_verdict([0, 1, 2, 3], v)
        rep = st.reputation
        assert rep[3] < 0.3
        assert np.all(rep[:3] > 0.6)

    def test_state_dict_roundtrip_and_shape_guard(self):
        st = ClientStatsStore(4, loss_window=3)
        st.record_loss(2, 1.5)
        st.record_availability(1, participated=False)
        st.record_verdict([0], [0.0])
        st2 = ClientStatsStore(4, loss_window=3)
        st2.load_state_dict(st.state_dict())
        for f in ClientStatsStore._FIELDS:
            np.testing.assert_array_equal(getattr(st, f), getattr(st2, f))
        with pytest.raises(ValueError):
            ClientStatsStore(5, loss_window=3).load_state_dict(
                st.state_dict())


# --- strategies -------------------------------------------------------------

class TestStrategies:
    def test_uniform_is_bit_identical_to_client_sampling(self):
        args = make_args(client_num_in_total=20, client_num_per_round=6)
        strat = create_strategy(args, 20, ClientStatsStore(20))
        for r in range(5):
            sampled, excluded = strat.select(r, 6)
            assert excluded == []
            assert sampled == client_sampling(r, 20, 6, stream="legacy")

    def test_power_of_choice_prefers_high_loss(self):
        args = make_args(client_selection="power_of_choice",
                         client_num_in_total=16, poc_d_factor=4.0)
        st = ClientStatsStore(16)
        for c in range(16):  # clients 12..15 have the highest losses
            st.record_loss(c, float(c))
        strat = create_strategy(args, 16, st)
        sampled, _ = strat.select(0, 4)
        # d=16 candidates == everyone, so top-4 by loss is exact
        assert sorted(sampled) == [12, 13, 14, 15]

    def test_oort_explores_then_exploits(self):
        args = make_args(client_selection="oort", client_num_in_total=12,
                         oort_explore_frac=0.5)
        st = ClientStatsStore(12)
        for c in range(6):  # half the population has history
            st.record_selected(0, [c])
            st.record_loss(c, 10.0 if c == 3 else 0.1)
        strat = create_strategy(args, 12, st)
        sampled, _ = strat.select(5, 4)
        assert len(sampled) == len(set(sampled)) == 4
        assert 3 in sampled  # highest-utility explored client
        # explore slots went to never-selected clients
        assert any(c >= 6 for c in sampled)

    def test_strategies_deterministic_given_history(self):
        for name in ("power_of_choice", "oort", "reputation"):
            args = make_args(client_selection=name, client_num_in_total=16)
            st = ClientStatsStore(16)
            for c in range(16):
                st.record_loss(c, float(16 - c))
                st.record_selected(0, [c])
            a = create_strategy(args, 16, st).select(7, 5)
            b = create_strategy(args, 16, st).select(7, 5)
            assert a == b

    def test_reputation_benches_low_rep_with_floor(self):
        args = make_args(client_selection="reputation",
                         client_num_in_total=8, client_num_per_round=8,
                         selection_rep_threshold=0.3,
                         selection_min_keep_frac=0.5)
        st = ClientStatsStore(8)
        for _ in range(8):  # five clients consistently excluded, three kept
            st.record_verdict(list(range(8)), [0.0] * 5 + [1.0] * 3)
        strat = create_strategy(args, 8, st)
        sampled, benched = strat.select(0, 8)
        assert sorted(sampled) == list(range(8))
        # five fall below the threshold, but the min-keep floor caps
        # benching at half the cohort
        assert len(benched) == 4
        assert set(benched) <= set(range(5))

    def test_unknown_strategy_raises(self):
        with pytest.raises(ValueError):
            create_strategy(make_args(client_selection="roulette"), 8,
                            ClientStatsStore(8))


# --- engine seam ------------------------------------------------------------

class TestEngineSelection:
    def test_default_schedules_bit_identical_to_legacy(self):
        """uniform + legacy stream (the defaults) must reproduce the
        pre-subsystem schedule EXACTLY: same sampled ids, same slot
        tensors, work all-ones."""
        args = make_args(client_num_in_total=8, client_num_per_round=5)
        sim = build_sim(args)
        assert not sim.selection.track  # passive at defaults
        from fedml_tpu.simulation.sampling import build_schedule
        for r in range(4):
            sampled, (idx, active, work), faults = sim._schedule_for(r)
            np.random.seed(r)  # the reference draw
            ref = list(np.random.choice(range(8), 5, replace=False))
            assert [int(c) for c in sampled] == [int(c) for c in ref]
            ref_idx, ref_active = build_schedule(ref, sim.n_devices,
                                                 sim.cpd,
                                                 max_slots=sim.cpd)
            np.testing.assert_array_equal(idx, ref_idx)
            np.testing.assert_array_equal(active, ref_active)
            assert np.all(work == 1.0)
            assert faults is None

    def test_default_run_params_unchanged_by_subsystem_knobs(self):
        """Spelling the default selection knobs explicitly must not move
        a single bit of the trajectory."""
        r_plain = fedml_tpu.run_simulation(backend="tpu", args=make_args())
        r_expl = fedml_tpu.run_simulation(backend="tpu", args=make_args(
            client_selection="uniform", sampling_stream="legacy",
            selection_adaptive_oversample=False))
        for a, b in zip(jax.tree_util.tree_leaves(r_plain["params"]),
                        jax.tree_util.tree_leaves(r_expl["params"])):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_reputation_benches_byzantine_clients_in_program(self):
        """Deterministic byzantine clients (ids 0..1) + multi_krum: the
        fused robust program's [K] verdict decays their reputation, and
        after a few rounds the reputation strategy benches them as
        work-0 slots (renormalized in-program dropout)."""
        args = make_args(client_num_in_total=8, client_num_per_round=8,
                         client_selection="reputation",
                         enable_defense=True, defense_type="multi_krum",
                         krum_param_m=6, byzantine_client_num=2,
                         enable_attack=True, attack_type="byzantine_flip",
                         attack_scale=5.0, comm_round=8)
        sim = build_sim(args)
        assert sim.robust_fused  # selection rides the fused program
        hyper = hyper_for(args)
        for r in range(6):
            sim.run_round(r, hyper)
        rep = sim.selection.store.reputation
        assert rep[0] < 0.3 and rep[1] < 0.3
        # honest clients stay above the bench threshold
        assert np.all(rep[2:] > 0.3)
        assert float(np.mean(rep[2:])) > 0.6
        # the NEXT schedule benches them: their slots carry work 0
        sampled, (idx, active, work), _ = sim._schedule_for(6)
        benched_work = {cid: work[d, s] for cid, d, s in
                        slot_placement(sampled, sim.n_devices, sim.cpd)}
        assert benched_work[0] == 0.0 and benched_work[1] == 0.0
        assert all(benched_work[c] == 1.0 for c in sampled
                   if c not in (0, 1))

    def test_adaptive_oversample_grows_cohort_from_posterior(self):
        args = make_args(client_num_in_total=16, client_num_per_round=4,
                         client_selection="uniform",
                         selection_adaptive_oversample=True,
                         selection_max_over_sample=1.0,
                         chaos_dropout_prob=0.4, chaos_seed=11)
        sim = build_sim(args)
        assert sim.selection.adaptive and sim.selection.track
        assert sim._sample_n == 8  # the cap, not the per-round draw
        # round 0: no history yet -> prior-dominated, near the base
        # (ceil(4 / (1 - 0.05-prior)) = 5)
        assert sim.selection.round_target(0, 4, 8) <= 5
        hyper = hyper_for(args)
        for r in range(8):
            sim.run_round(r, hyper)
        # ~40% observed dropout -> posterior sizes the cohort up
        target = sim.selection.round_target(8, 4, 8)
        assert target >= 6
        post = sim.selection.store.population_dropout_mean()
        assert 0.2 < post < 0.6

    def test_canonical_width_and_compile_once_with_selection(
            self, xla_compile_counter):
        """The fused robust program must compile exactly once per run with
        selection + adaptive over-sampling enabled — cohort-size changes
        ride the canonical width as masked padding, never a new shape."""
        args = make_args(client_num_in_total=8, client_num_per_round=4,
                         client_selection="oort",
                         selection_adaptive_oversample=True,
                         chaos_dropout_prob=0.25, chaos_seed=5,
                         enable_defense=True, defense_type="multi_krum",
                         krum_param_m=2, byzantine_client_num=1,
                         comm_round=12)
        sim = build_sim(args)
        assert sim.robust_fused
        hyper = hyper_for(args)
        sim.run_rounds_fused(0, 4, hyper)  # warmup compiles everything
        xla_compile_counter.reset()
        sim.run_rounds_fused(4, 4, hyper)
        sim.run_rounds_fused(8, 4, hyper)
        assert xla_compile_counter.delta() == 0
        assert sim.dispatch_stats["dispatches"] == 3

    def test_crash_resume_replays_identical_selections(self, tmp_path):
        """The store rides RoundCheckpointer: a crashed-and-resumed run
        must keep selecting the SAME cohorts as the uninterrupted one."""
        kw = dict(client_num_in_total=16, client_num_per_round=4,
                  client_selection="power_of_choice",
                  chaos_dropout_prob=0.2, chaos_seed=3, comm_round=8,
                  checkpoint_every_rounds=2, frequency_of_the_test=100)
        args_a = make_args(checkpoint_dir=str(tmp_path / "a"), **kw)
        sim_a = build_sim(args_a)
        sim_a.run()

        from fedml_tpu.core.chaos import ChaosCrash
        args_b = make_args(checkpoint_dir=str(tmp_path / "b"),
                           chaos_crash_at_round=3, **kw)
        crashed = False
        try:
            build_sim(args_b).run()
        except ChaosCrash as e:
            crashed = True
            assert e.round_idx == 3
        assert crashed
        args_b2 = make_args(checkpoint_dir=str(tmp_path / "b"), **kw)
        sim_b = build_sim(args_b2)
        sim_b.run()  # resumes from the round-3 checkpoint (incl. store)
        # identical post-run selection state => identical future cohorts.
        # Cohort-driving counters must match EXACTLY; observed loss/EMA
        # floats may drift at last-ulp scale between separately compiled
        # program instances (amplified over post-restore rounds), which
        # is outside the subsystem's determinism contract — the schedule
        # comparison below is what guards against a drift large enough
        # to flip a selection.
        sa, sb = sim_a.selection.state_dict(), sim_b.selection.state_dict()
        # these counters are written once per SELECTED (round, client):
        # exact equality proves the resumed run's rounds 4-7 cohorts were
        # identical to the uninterrupted run's — the replay claim
        for field in ("loss_count", "loss_ptr", "times_selected",
                      "last_selected", "drop_obs", "part_obs", "incl_obs",
                      "excl_obs", "has_latency"):
            np.testing.assert_array_equal(sa[field], sb[field],
                                          err_msg=field)
        for field in ("losses", "ema_latency", "ema_work"):
            np.testing.assert_allclose(sa[field], sb[field], atol=1e-2,
                                       err_msg=field)
        # and selections are a pure function of (seed, round, store): a
        # manager rebuilt from the checkpointed state must produce the
        # same future cohorts as the live one
        rebuilt = SelectionManager(args_b2, 16)
        rebuilt.load_state_dict(sb)
        for r in range(8, 12):
            assert rebuilt.select(r, 4) == sim_b.selection.select(r, 4)

    def test_selection_state_only_checkpointed_when_stateful(self):
        sim = build_sim(make_args())
        assert "selection" not in sim._ckpt_state()
        sim2 = build_sim(make_args(client_selection="oort",
                                   client_num_per_round=4))
        st = sim2._ckpt_state()
        assert "selection" in st
        assert isinstance(st["selection"], dict)

    def test_host_robust_path_feeds_reputation(self):
        """sharded_defense: false (host kernels) still yields verdicts via
        the defense info dict — reputation works on every robust path."""
        args = make_args(client_num_in_total=8, client_num_per_round=8,
                         client_selection="reputation",
                         enable_defense=True, defense_type="multi_krum",
                         krum_param_m=6, byzantine_client_num=2,
                         enable_attack=True, attack_type="byzantine_flip",
                         attack_scale=5.0, robust_fused="host")
        sim = build_sim(args)
        assert not sim.robust_fused
        hyper = hyper_for(args)
        for r in range(4):
            sim.run_round(r, hyper)
        sim.selection._flush()
        rep = sim.selection.store.reputation
        assert rep[0] < 1.0 and rep[1] < 1.0
        assert np.all(rep[2:] >= rep[0])


# --- sharded defense verdicts ----------------------------------------------

class TestDefenseVerdicts:
    def test_sharded_verdict_flags_byzantine_rows(self):
        from fedml_tpu.core.mesh import build_mesh
        from fedml_tpu.core.security.defense import sharded
        from fedml_tpu.constants import AXIS_CLIENT
        mesh = build_mesh(None)
        rng = np.random.default_rng(0)
        mat = rng.normal(size=(8, 32)).astype(np.float32)
        mat[:2] += 50.0  # two obvious outliers
        w = np.ones(8, np.float32)
        vec, verdict = sharded.defend_matrix_sharded(
            mesh, AXIS_CLIENT, jnp.asarray(mat), w, "multi_krum",
            byzantine_count=2, multi_k=4, return_verdict=True)
        v = np.asarray(verdict)
        assert v.shape == (8,)
        assert v[0] == 0.0 and v[1] == 0.0
        assert int(np.sum(v)) == 4  # multi_k selected rows

    def test_verdict_all_ones_for_coordinatewise_defense(self):
        from fedml_tpu.core.mesh import build_mesh
        from fedml_tpu.core.security.defense import sharded
        from fedml_tpu.constants import AXIS_CLIENT
        mesh = build_mesh(None)
        mat = np.random.default_rng(1).normal(size=(6, 16)).astype(
            np.float32)
        _, verdict = sharded.defend_matrix_sharded(
            mesh, AXIS_CLIENT, jnp.asarray(mat), np.ones(6, np.float32),
            "coordinate_median", return_verdict=True)
        np.testing.assert_array_equal(np.asarray(verdict), np.ones(6))


# --- cross-silo silo selection ----------------------------------------------

class TestSiloSelection:
    def _agg(self, **kw):
        from fedml_tpu.cross_silo.server.fedml_aggregator import (
            FedMLAggregator)
        args = make_args(training_type="cross_silo",
                         client_num_per_round=4, **kw)
        return FedMLAggregator(args, {"w": jnp.zeros(3)})

    def test_uniform_never_benches(self):
        agg = self._agg()
        for _ in range(5):
            agg.observe_round([1, 2], [1, 2, 3, 4])
        assert agg.select_silos([1, 2, 3, 4]) == [1, 2, 3, 4]

    def test_flaky_silo_benched_with_quorum_floor(self):
        agg = self._agg(client_selection="reputation",
                        round_quorum_frac=0.5)
        for _ in range(10):  # silo 4 never reports
            agg.observe_round([1, 2, 3], [1, 2, 3, 4])
        assert agg.select_silos([1, 2, 3, 4]) == [1, 2, 3]
        # min-keep floor: even if everyone looks flaky, quorum survives
        for _ in range(20):
            agg.observe_round([], [1, 2, 3, 4])
        kept = agg.select_silos([1, 2, 3, 4])
        assert len(kept) >= 2  # ceil(0.5 * 4)

    def test_round_expected_shrinks_barrier(self):
        agg = self._agg()
        agg.set_round_expected(2)
        agg.add_local_trained_result(1, {"w": jnp.ones(3)}, 1.0)
        assert not agg.check_whether_all_receive()
        agg.add_local_trained_result(2, {"w": jnp.ones(3)}, 1.0)
        assert agg.check_whether_all_receive()
        agg.aggregate()
        # _reset_round restores the full-cohort barrier
        assert agg._expected == agg.client_num

    def test_upload_latency_observed(self):
        agg = self._agg(client_selection="oort")
        agg.observe_upload(2, 1.5)
        agg.observe_upload(2, 2.5)
        assert agg.silo_stats.has_latency[2] == 1.0
        assert 1.5 <= agg.silo_stats.ema_latency[2] <= 2.5


# --- mlops record -----------------------------------------------------------

def test_log_selection_record(tmp_path):
    import json
    from fedml_tpu.core import mlops
    args = make_args(log_file_dir=str(tmp_path), run_id="sel_test")
    mlops.init(args)
    try:
        mlops.log_selection(round_idx=3, strategy="oort", sampled=[1, 2],
                            excluded=[7], target_n=2,
                            dropout_posterior=0.125)
    finally:
        # uninstall, not just close: a closed-but-installed sink would
        # blow up every later test that emits a record
        mlops._state["sink"].close()
        mlops._state["sink"] = None
        mlops._state["enabled"] = False
    recs = [json.loads(l) for l in
            open(tmp_path / "run_sel_test.jsonl")]
    sel = [r for r in recs if r["kind"] == "selection"]
    assert sel and sel[0]["strategy"] == "oort"
    assert sel[0]["sampled"] == [1, 2] and sel[0]["excluded"] == [7]
    assert sel[0]["round_idx"] == 3


# --- review regressions ------------------------------------------------------

class TestReviewRegressions:
    def test_quorum_restored_after_benched_round(self):
        """A quorum scaled down by set_round_expected must not leak into
        later rounds that bench nobody."""
        from fedml_tpu.cross_silo.server.fedml_aggregator import (
            FedMLAggregator)
        args = make_args(training_type="cross_silo",
                         client_num_in_total=10, client_num_per_round=10,
                         round_quorum_frac=0.8)
        agg = FedMLAggregator(args, {"w": jnp.zeros(3)})
        assert agg.quorum == 8
        agg.set_round_expected(6)
        assert agg.quorum == 5
        agg.add_local_trained_result(1, {"w": jnp.ones(3)}, 1.0)
        agg.aggregate()  # _reset_round
        assert agg.quorum == 8 and agg._expected == 10

    def test_benched_silo_not_branded_and_redeems(self):
        """Dropout evidence comes from the SELECTED cohort only; a benched
        silo that reports anyway heals its posterior (redemption)."""
        from fedml_tpu.cross_silo.server.fedml_aggregator import (
            FedMLAggregator)
        agg = FedMLAggregator(
            make_args(training_type="cross_silo", client_num_per_round=4,
                      client_selection="reputation"),
            {"w": jnp.zeros(3)})
        # silo 4 benched (not in expected) and silent: NO evidence at all
        agg.observe_round(reported=[1, 2, 3], expected=[1, 2, 3])
        assert agg.silo_stats.drop_obs[4] == 0.0
        assert agg.silo_stats.part_obs[4] == 0.0
        # benched silo reports anyway: participation evidence (healing)
        agg.observe_round(reported=[1, 2, 3, 4], expected=[1, 2, 3])
        assert agg.silo_stats.part_obs[4] == 1.0
        assert agg.silo_stats.drop_obs[4] == 0.0

    def test_verdict_from_info_rejects_index_arrays(self):
        """Host bulyan's info['selected'] carries top-theta row INDICES —
        a shape-only check would brand arbitrary clients when theta == k;
        only binary masks (and in-[0,1] continuous weights) qualify."""
        from fedml_tpu.simulation.tpu.engine import _verdict_from_info
        k = 4
        # bulyan-style index array: shape (k,) but NOT a mask -> rejected
        assert _verdict_from_info({"selected": np.array([2, 0, 3, 1])},
                                  k) is None
        # krum-style binary mask -> accepted
        mask = np.array([0.0, 1.0, 1.0, 0.0])
        np.testing.assert_array_equal(
            _verdict_from_info({"selected": mask}, k), mask)
        # continuous weights outside [0, 1] -> rejected; inside -> kept
        assert _verdict_from_info({"fg_weights": np.array(
            [0.5, 1.2, 0.1, 0.0])}, k) is None
        w = np.array([0.5, 0.9, 0.1, 0.0], np.float32)
        np.testing.assert_array_equal(
            _verdict_from_info({"fg_weights": w}, k), w)
        # wrong shape -> rejected
        assert _verdict_from_info({"kept": np.ones(3)}, k) is None

    def test_adaptive_pinned_under_fused_robust(self):
        """The fused robust program bakes the [K] cohort shape into the
        compiled defense kernel: a posterior-driven cohort-size flip
        would crash the fused stack mid-block and recompile across
        blocks, so adaptive over-sampling is PINNED (loudly) under
        robust_fused — and a long run can no longer crash."""
        args = make_args(client_num_in_total=8, client_num_per_round=4,
                         selection_adaptive_oversample=True,
                         chaos_dropout_prob=0.3, chaos_seed=2,
                         enable_defense=True, defense_type="multi_krum",
                         krum_param_m=2, byzantine_client_num=1,
                         comm_round=24, frequency_of_the_test=1000)
        sim = build_sim(args)
        assert sim.robust_fused
        assert not sim.selection.adaptive
        assert sim._sample_n == sim._static_n
        hyper = hyper_for(args)
        for start in range(0, 24, 8):  # enough observations to have
            sim.run_rounds_fused(start, 8, hyper)  # flipped an unpinned
        assert sim.dispatch_stats["dispatches"] == 3  # target mid-run

    def test_nonrobust_fused_adaptive_flip_keeps_compile_once(
            self, xla_compile_counter):
        """Without a defense the cohort-size flip rides canonical-width
        padding: the target moves, the compiled shapes do not."""
        args = make_args(client_num_in_total=16, client_num_per_round=4,
                         selection_adaptive_oversample=True,
                         chaos_dropout_prob=0.4, chaos_seed=11,
                         comm_round=24, frequency_of_the_test=1000)
        sim = build_sim(args)
        assert sim.selection.adaptive  # no pin without robust fusion
        hyper = hyper_for(args)
        sim.run_rounds_fused(0, 8, hyper)  # warmup compiles everything
        xla_compile_counter.reset()
        sim.run_rounds_fused(8, 8, hyper)
        sim.run_rounds_fused(16, 8, hyper)
        assert xla_compile_counter.delta() == 0
        # the adaptive target genuinely moved while shapes stayed put
        assert sim.selection.round_target(24, 4, sim._sample_n) > 4

    def test_reputation_refuses_intolerant_aggregation(self):
        """Benching rides the work-0 channel, which only renormalizes
        under chaos_tolerance — the intolerant combination would dilute
        every round and must be refused, not silently degrade."""
        with pytest.raises(ValueError, match="chaos_tolerance"):
            build_sim(make_args(client_selection="reputation",
                                chaos_tolerance=False))

    def test_adaptive_base_replaces_static_over_sample(self):
        """Adaptive sizing REPLACES chaos_over_sample (documented
        semantics): at a cold-start posterior of ~5% the cohort sits near
        k, not at the static 1.5k inflation."""
        args = make_args(client_num_in_total=16, client_num_per_round=4,
                         chaos_over_sample=0.5,
                         selection_adaptive_oversample=True)
        sim = build_sim(args)
        assert sim._static_n == 6  # ceil(4 * 1.5): the static inflation
        sampled, _, _ = sim._schedule_for(0)
        assert len(sampled) <= 5  # ceil(4 / 0.95), NOT the static 6
