"""Model zoo shape tests (reference test analogue: ``model/cv/test_cnn.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.arguments import Arguments
from fedml_tpu.model import create


@pytest.mark.parametrize("name,shape,classes", [
    ("lr", (2, 784), 10),
    ("mlp", (2, 784), 10),
    ("cnn", (2, 28, 28, 1), 62),
    ("simple_cnn", (2, 32, 32, 3), 10),
    ("resnet20", (2, 32, 32, 3), 10),
    ("resnet56", (2, 32, 32, 3), 10),
    ("resnet18", (2, 32, 32, 3), 10),
    # the two largest zoo models compile ~80s each on the CPU mesh —
    # slow tier so the quick gate stays under 10 minutes
    pytest.param("mobilenet_v3", (2, 32, 32, 3), 62,
                 marks=pytest.mark.slow),
    pytest.param("efficientnet-b0", (2, 32, 32, 3), 10,
                 marks=pytest.mark.slow),
    ("vgg11", (2, 32, 32, 3), 10),
])
def test_model_forward_shapes(name, shape, classes):
    args = Arguments(model=name)
    bundle = create(args, classes)
    x = jnp.zeros(shape, jnp.float32)
    params = bundle.init(jax.random.PRNGKey(0), x)
    out = bundle.apply(params, x)
    assert out.shape == (shape[0], classes)


def test_rnn_per_token_logits():
    args = Arguments(model="rnn")
    bundle = create(args, 64)
    x = jnp.zeros((2, 16), jnp.int32)
    params = bundle.init(jax.random.PRNGKey(0), x)
    out = bundle.apply(params, x)
    assert out.shape == (2, 16, 64)


def test_gan_pair():
    gen, disc = create(Arguments(model="gan"), 10)
    z = jnp.zeros((2, 100))
    gp = gen.init(jax.random.PRNGKey(0), z)
    img = gen.apply(gp, z)
    assert img.shape == (2, 784)
    dp = disc.init(jax.random.PRNGKey(1), img)
    score = disc.apply(dp, img)
    assert score.shape == (2, 1)


def test_stackoverflow_rnn_selected_by_dataset():
    args = Arguments(model="rnn", dataset="stackoverflow_nwp")
    bundle = create(args, 64)
    x = jnp.zeros((2, 10), jnp.int32)
    params = bundle.init(jax.random.PRNGKey(0), x)
    assert bundle.apply(params, x).shape == (2, 10, 64)


def test_unknown_model_raises():
    with pytest.raises(ValueError):
        create(Arguments(model="transformerXL"), 10)


def test_sequence_task_end_to_end():
    """shakespeare-style NWP with LSTM trains through both backends."""
    import fedml_tpu
    args = Arguments(dataset="synthetic_shakespeare", model="rnn",
                     client_num_in_total=4, client_num_per_round=4,
                     comm_round=2, batch_size=8, learning_rate=0.5,
                     frequency_of_the_test=1, random_seed=0)
    r = fedml_tpu.run_simulation(backend="tpu", args=args)
    assert np.isfinite(r["final_test_acc"])


def test_bf16_precision_path():
    """args.precision selects a bf16 compute path: master params stay f32,
    activations/matmuls run in bfloat16, training still learns."""
    import jax
    import jax.numpy as jnp
    import fedml_tpu
    args = Arguments(dataset="synthetic_mnist", model="mlp",
                     precision="bfloat16", client_num_in_total=4,
                     client_num_per_round=4, comm_round=3, batch_size=16,
                     learning_rate=0.1, frequency_of_the_test=2,
                     random_seed=0)
    bundle = create(args, 10)
    assert bundle.compute_dtype == jnp.bfloat16
    x = jnp.zeros((2, 784), jnp.float32)
    params = bundle.init(jax.random.PRNGKey(0), x)
    # master params are f32; the apply output is cast back to f32
    assert all(l.dtype == jnp.float32
               for l in jax.tree_util.tree_leaves(params))
    assert bundle.apply(params, x).dtype == jnp.float32
    # bf16 actually reaches the matmuls: jaxpr of the fwd contains bf16 dot
    jaxpr = str(jax.make_jaxpr(lambda p, x: bundle.apply(p, x))(params, x))
    assert "bf16" in jaxpr
    r = fedml_tpu.run_simulation(backend="tpu", args=args)
    assert np.isfinite(r["final_test_acc"])
    assert r["final_test_acc"] > 0.3


def test_lenet_and_finance_models_forward():
    import jax
    import jax.numpy as jnp
    for name, shape, out in (("lenet", (2, 28, 28, 1), 10),
                             ("vfl_feature_extractor", (2, 30), 16),
                             ("vfl_classifier", (2, 48), 2),
                             ("lending_club_mlp", (2, 90), 2)):
        bundle = create(Arguments(model=name), out)
        x = jnp.zeros(shape, jnp.float32)
        params = bundle.init(jax.random.PRNGKey(0), x)
        assert bundle.apply(params, x).shape == (2, out)


def test_federated_serving_session(tmp_path):
    """training_type=fedml_serving: FL session ends with a live endpoint."""
    import json
    import threading
    import urllib.request
    from fedml_tpu import data as data_mod
    from fedml_tpu.core.distributed.communication.inproc import InProcBroker
    from fedml_tpu.cross_silo.horizontal.runner import build_client
    from fedml_tpu.runner import FedMLRunner
    args = Arguments(dataset="synthetic_mnist", model="lr",
                     client_num_in_total=2, client_num_per_round=2,
                     comm_round=2, epochs=1, batch_size=32,
                     learning_rate=0.1, frequency_of_the_test=1,
                     random_seed=7, training_type="fedml_serving",
                     role="server", backend="INPROC")
    broker = InProcBroker()
    args.inproc_broker = broker
    fed, output_dim = data_mod.load(args)
    bundle = create(args, output_dim)
    clients = [build_client(args, fed, bundle, rank=r, backend="INPROC")
               for r in (1, 2)]
    for c in clients:
        threading.Thread(target=c.run, daemon=True).start()
    runner = FedMLRunner(args, dataset=fed, model=bundle)
    result = runner.run()
    assert result["final_test_acc"] > 0.6
    port = result["serving_port"]
    x = np.zeros((1, 784), np.float32).tolist()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps({"inputs": x}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        out = json.load(r)
    assert len(out["outputs"][0]) == 10
    runner.runner.inference_runner.stop()
