"""Worker for test_cross_device_multiprocess: one role (server or device)
of a cross-device (Beehive) FL session over real gRPC sockets, driven
through the public ``CrossDeviceRunner``. Devices can run the NATIVE C++
engine — a separate OS process running native local training against a
Python server is exactly the reference's MobileNN deployment shape.

Usage: cross_device_worker.py <role> <rank> <base_port> <cache_dir>
                              <engine> <out>
"""

import json
import os
import sys


def main() -> None:
    role, rank, base_port, cache_dir, engine, out_path = sys.argv[1:7]
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from fedml_tpu import data as data_mod, model as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.cross_device.runner import CrossDeviceRunner

    args = Arguments(
        dataset="digits", model="lr", client_num_in_total=2,
        client_num_per_round=2, comm_round=2, epochs=1, batch_size=32,
        learning_rate=0.2, random_seed=3, training_type="cross_device",
        backend="GRPC", grpc_base_port=int(base_port), role=role,
        rank=int(rank), model_file_cache_dir=cache_dir,
        round_timeout_s=30.0,
        device_engine=(engine if engine != "-" else "jax"))
    fed, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    runner = CrossDeviceRunner(args, fed, bundle)
    result = runner.run()

    if role == "server":
        hist = (result or {}).get("history") or []
        engines = {str(did): d.get("engine") for did, d in
                   getattr(runner.manager, "devices_online", {}).items()}
        with open(out_path, "w") as f:
            json.dump({"rounds": len(hist),
                       "final_test_acc": (result or {}).get(
                           "final_test_acc"),
                       "engines": engines,
                       "device_eval_accs": [r.get("device_eval_acc")
                                            for r in hist]}, f)


if __name__ == "__main__":
    main()
