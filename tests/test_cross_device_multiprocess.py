"""Cross-device (Beehive) FL session across OS processes over real gRPC
sockets: server + 2 devices as separate interpreters, one device running
the NATIVE C++ engine — the reference's MobileNN deployment shape (a
native device process talking to a Python aggregation server), extending
the multi-process story to the third pillar."""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "cross_device_worker.py")

# ranks listen on base_port + rank: reuse the gRPC session test's helpers
# that probe the whole block free and wait for the server listener
from tests.test_grpc_session import _free_port_block, _wait_listening


def test_cross_device_grpc_session_with_native_device(tmp_path):
    from fedml_tpu import native
    if not native.available():
        pytest.skip("no native toolchain")

    base = _free_port_block(4)
    cache = str(tmp_path / "model_cache")
    os.makedirs(cache, exist_ok=True)
    out_path = str(tmp_path / "result.json")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"

    def spawn(role, rank, engine):
        return subprocess.Popen(
            [sys.executable, WORKER, role, str(rank), str(base), cache,
             engine, out_path], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    procs = [spawn("server", 0, "-")]
    try:
        _wait_listening(base)
        procs.append(spawn("device", 1, "native"))
        procs.append(spawn("device", 2, "-"))
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("cross-device gRPC session timed out")
            outs.append(out.decode(errors="replace"))
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    with open(out_path) as f:
        res = json.load(f)
    assert res["rounds"] == 2
    assert res["final_test_acc"] is not None and res["final_test_acc"] > 0.3
    # the NATIVE device (its own OS process) evaluated the global model
    # on-device and the server recorded it each round
    accs = [a for a in res["device_eval_accs"] if a is not None]
    assert len(accs) == 2 and all(0.0 <= a <= 1.0 for a in accs)
    # the native engine actually ran in the child process (a silent
    # fallback to jax would register as engine='jax')
    assert res["engines"].get("1") == "native", res["engines"]
    assert res["engines"].get("2") == "jax" 
