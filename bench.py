"""Benchmark: FL round throughput of the jitted mesh engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

The reference publishes no benchmark numbers (BASELINE.md), so the baseline
here is the reference's own *architecture* on identical hardware: the
single-process golden loop (per-client dispatch + host-side aggregation —
the shape of ``sp/fedavg/fedavg_api.py``) vs our fused whole-round SPMD
program. ``vs_baseline`` = mesh rounds/hour ÷ golden-loop rounds/hour.

Workload: the BASELINE.md north-star *shape* — FedAvg ResNet-56, 64 clients
per round (multi-client-per-chip scan), bf16 compute. Real CIFAR-10 is used
when it is cached or downloadable; otherwise the run falls back (loudly,
and labeled in the output) to a synthetic stand-in of identical shape —
throughput is shape-determined either way.

Besides rounds/hour the line reports ``step_time_s``, achieved ``tflops``
and ``mfu`` (vs the chip's bf16 peak), computed from XLA's own
cost-analysis FLOP count for the compiled round program.
"""

from __future__ import annotations

import json
import time


# bf16 peak TFLOP/s per chip, by device-kind substring (public specs)
_PEAK_TFLOPS = (
    ("v6", 918.0), ("v5p", 459.0), ("v5e", 197.0), ("v5", 197.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 45.0), ("cpu", 0.5),
)


def _peak_tflops(device):
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, peak in _PEAK_TFLOPS:
        if key in kind:
            return peak
    return None  # unknown accelerator: report mfu as null, not a guess


def run():
    import jax
    import jax.numpy as jnp

    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.algframe.client_trainer import ClassificationTrainer
    from fedml_tpu.core.algframe.types import TrainHyper
    from fedml_tpu.data import load
    from fedml_tpu.model import create
    from fedml_tpu.optimizers.registry import create_optimizer
    from fedml_tpu.simulation.sp.simulator import SPSimulator
    from fedml_tpu.simulation.tpu.engine import TPUSimulator

    n_clients = 64
    args = Arguments(
        dataset="cifar10", model="resnet56", precision="bfloat16",
        client_num_in_total=n_clients, client_num_per_round=n_clients,
        comm_round=1, epochs=1, batch_size=32, learning_rate=0.1,
        frequency_of_the_test=10_000, random_seed=0,
        allow_synthetic=True,  # loud, labeled fallback when no net/cache
        synthetic_size=50_000,  # stand-in matches real CIFAR-10's workload
    )
    fed, output_dim = load(args)
    provenance = getattr(fed, "provenance", "real")
    bundle = create(args, output_dim)
    spec = ClassificationTrainer(bundle.apply)
    hyper = TrainHyper(learning_rate=jnp.float32(args.learning_rate), epochs=1)

    def force(params):
        # NB: block_until_ready does not reliably synchronize on the tunneled
        # TPU platform — force a scalar readback to time actual execution.
        return float(jax.tree_util.tree_leaves(params)[0].sum())

    def time_rounds(run_one, params_of, warmup=1, iters=3):
        for _ in range(warmup):
            run_one()
        force(params_of())
        t0 = time.perf_counter()
        for _ in range(iters):
            run_one()
            force(params_of())
        return (time.perf_counter() - t0) / iters

    # --- mesh engine (ours): whole round = one jitted SPMD program
    opt = create_optimizer(args, spec)
    tpu_sim = TPUSimulator(args, fed, bundle, opt, spec)
    r = [0]

    def tpu_round():
        tpu_sim.run_round(r[0], hyper)
        r[0] += 1

    tpu_round_s = time_rounds(tpu_round, lambda: tpu_sim.params)

    # FLOPs of the compiled round program (XLA cost analysis), for MFU
    flops = tpu_sim.round_cost_flops(hyper)
    n_dev = tpu_sim.n_devices
    achieved_tflops = (flops / tpu_round_s) / 1e12 if flops else 0.0
    peak_per_chip = _peak_tflops(jax.devices()[0])
    mfu = (achieved_tflops / (peak_per_chip * n_dev)
           if peak_per_chip else None)

    # --- baseline: golden per-client loop (reference SP architecture),
    # scaled down (8 of 64 clients) then normalized — the full 64-client
    # python loop would dominate bench wall-clock for no extra information.
    base_clients = 8
    bargs = Arguments(
        dataset="cifar10", model="resnet56", precision="bfloat16",
        client_num_in_total=base_clients, client_num_per_round=base_clients,
        comm_round=1, epochs=1, batch_size=32, learning_rate=0.1,
        frequency_of_the_test=10_000, random_seed=0, allow_synthetic=True,
        # same per-client workload as the 64-client run, whether the loader
        # produced real or synthetic data (vs_baseline is per-sample
        # normalized; this only bounds the baseline's wall-clock)
        synthetic_size=6_250, max_total_samples=6_250,
    )
    bfed, _ = load(bargs)
    sp_sim = SPSimulator(bargs, bfed, bundle, create_optimizer(bargs, spec),
                         spec)

    def sp_round():
        sp_sim.run(comm_round=1)

    sp_round_s = time_rounds(sp_round, lambda: sp_sim.params,
                             warmup=1, iters=2)
    # normalize per *training sample* so the comparison is fair whether the
    # loader produced real data (both runs see the full dataset) or the
    # per-client-matched synthetic stand-ins
    tpu_samples = float(fed.total_train_samples)
    sp_samples = float(bfed.total_train_samples)
    rounds_per_hour = 3600.0 / tpu_round_s
    vs_baseline = (sp_round_s / sp_samples) / (tpu_round_s / tpu_samples)
    print(json.dumps({
        "metric": "fedavg_resnet56_cifar10_rounds_per_hour",
        "value": round(rounds_per_hour, 1),
        "unit": f"rounds/hour (64 clients/round, 1 local epoch, bf16, "
                f"{provenance} data)",
        "vs_baseline": round(vs_baseline, 3),
        "step_time_s": round(tpu_round_s, 4),
        "tflops": round(achieved_tflops, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "n_devices": n_dev,
        "data_provenance": provenance,
    }))


if __name__ == "__main__":
    run()
